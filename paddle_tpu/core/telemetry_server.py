"""Live telemetry export: the HTTP surface a fleet scrapes and probes.

Reference analog: the reference's serving deployments sit behind
monitoring sidecars scraping process stats; our PR-2 registry and
Perfetto export only answer questions when a developer attaches a
Profiler in-process. This module makes a live replica observable from
the OUTSIDE — a stdlib ``ThreadingHTTPServer`` (no new dependencies)
exposing:

    /metrics          Prometheus text rendering of the FULL registry —
                      counters, gauges (+ ``_peak``), histograms with
                      cumulative ``_bucket{le=...}`` lines
    /healthz          200 while the process is alive (liveness probe)
    /readyz           200/503 from ``ServingEngine.health()`` — warm,
                      not draining, queue below bound; flips 503 the
                      moment a GracefulShutdown drain starts, so a
                      multi-replica router stops sending traffic BEFORE
                      the queue starts rejecting
    /flightrecorder   the flight recorder's dump (Perfetto JSON +
                      plaintext tail) on demand, no file writes
    /fleet/metrics    the FLEET registry — every rank's series merged
                      with ``rank=``/``replica=``/``incarnation=``
                      labels by an attached ``FleetAggregator`` (404
                      when this process is not the aggregator)
    /fleet/healthz    per-replica ready/reason/headroom rollup — the
                      multi-replica router's admission document
    /router           the fleet router's ``describe()`` document — the
                      live replica table (breaker state, drain flag,
                      health summary, admission score) plus routing
                      totals (404 when no router attached)
    /slo              the SLO watchtower document: every objective's
                      alert state + burn rates, the bounded alert
                      history, the top-K most expensive requests
                      (``Request.cost()`` attribution), and — when a
                      fleet aggregator is attached — the fleet-scope
                      evaluation + straggler ranks

Every ``/metrics``-style render also carries two scrape-hygiene
series: a ``paddle_build_info`` info-gauge (version, jax/jaxlib,
backend platform as labels, value pinned 1 — what dashboards key
deploy markers on) and ``process_uptime_seconds``.

Opt-in: ``PADDLE_TELEMETRY_PORT`` (the ServingEngine reads it, any
other process can call ``start_from_env()``/``TelemetryServer``
directly), or ``ServingEngine(telemetry_port=...)`` / ``Config.
enable_serving(telemetry_port=...)``. Port 0 binds an ephemeral port
(tests; ``server.port`` reports the real one).

The registry render reads through ``metrics.all_metrics()`` —
scraping never mutates, never locks the whole registry, and works
whether or not the monitor is currently enabled (a disabled monitor
scrapes as its last recorded values, which is exactly what a dashboard
wants during a wedge).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from . import flight_recorder, metrics, monitor

__all__ = ["TelemetryServer", "prometheus_text", "start_from_env"]


# ---------------------------------------------------- prometheus render

def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch == "_" or (ch == ":" and i):
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out)
    return "_" + s if s[:1].isdigit() else s


def _prom_labels(labels: str) -> str:
    """Our ``k=v,k2=v2`` label tail -> ``{k="v",k2="v2"}``."""
    if not labels:
        return ""
    parts = []
    for kv in labels.split(","):
        k, _, v = kv.partition("=")
        v = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        parts.append(f'{_prom_name(k)}="{v}"')
    return "{" + ",".join(parts) + "}"


def _split_key(key: str) -> Tuple[str, str]:
    """Registry key -> (base name, raw label tail): ``serve.requests``
    or ``serve.requests{status=completed}``."""
    if key.endswith("}") and "{" in key:
        base, _, rest = key.partition("{")
        return base, rest[:-1]
    return key, ""


def _finite(v: float) -> float:
    # the render must never emit NaN/inf (the Histogram.percentile
    # contract, applied to every exported number)
    v = float(v)
    return v if v - v == 0.0 else 0.0


# ------------------------------------------------- scrape hygiene lines

_START_MONOTONIC = time.monotonic()   # ≈ process start (core imports
#                                       run before any serving loop)
_BUILD_INFO_LINE: Optional[str] = None


def _build_info_line() -> str:
    """The ``paddle_build_info`` info-gauge sample line (computed once:
    versions don't change mid-process). Value pinned 1 — the labels
    carry the information, the standard Prometheus *_info idiom."""
    global _BUILD_INFO_LINE
    if _BUILD_INFO_LINE is None:
        labels = {}
        try:
            from .. import __version__
            labels["version"] = str(__version__)
        except Exception:
            labels["version"] = "unknown"
        try:
            import jax
            import jaxlib
            labels["jax"] = getattr(jax, "__version__", "unknown")
            labels["jaxlib"] = getattr(jaxlib, "__version__", "unknown")
            labels["platform"] = jax.default_backend()
        except Exception:
            labels.setdefault("jax", "unavailable")
            labels.setdefault("jaxlib", "unavailable")
            labels.setdefault("platform", "unknown")
        tail = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        _BUILD_INFO_LINE = f"paddle_build_info{_prom_labels(tail)} 1"
    return _BUILD_INFO_LINE


def _hygiene_lines() -> list:
    """Appended to EVERY /metrics-style render (process and fleet):
    the build-info gauge and the process uptime — standard scrape
    hygiene a router keys dashboards on."""
    return [
        "# TYPE paddle_build_info gauge",
        _build_info_line(),
        "# TYPE process_uptime_seconds gauge",
        f"process_uptime_seconds "
        f"{_finite(time.monotonic() - _START_MONOTONIC)!r}",
    ]


def prometheus_text(registry: Optional[dict] = None) -> str:
    """Render the metrics registry in the Prometheus text exposition
    format (version 0.0.4): one ``# TYPE`` line per metric family, then
    one sample line per label set. Histograms export cumulative
    ``_bucket`` lines (``+Inf`` == ``_count``), ``_sum`` and
    ``_count``; gauges also export a ``_peak`` companion gauge."""
    reg = registry if registry is not None else metrics.all_metrics()
    families: dict = {}
    for key in sorted(reg):
        base, labels = _split_key(key)
        families.setdefault(base, []).append((labels, reg[key]))
    lines = []
    for base in sorted(families):
        name = _prom_name(base)
        entries = families[base]
        kind = entries[0][1].kind
        lines.append(f"# TYPE {name} {kind}")
        for labels, m in entries:
            lab = _prom_labels(labels)
            if isinstance(m, metrics.Counter):
                lines.append(f"{name}{lab} {m.value}")
            elif isinstance(m, metrics.Gauge):
                # repr, not %g: a byte-scale gauge must not lose the
                # low digits a leak detector diffs between scrapes
                lines.append(f"{name}{lab} {_finite(m.value)!r}")
            elif isinstance(m, metrics.Histogram):
                bounds, counts, count, total = m.raw()
                cum = 0
                inner = labels.split(",") if labels else []
                for b, c in zip(bounds, counts):
                    cum += c
                    le = ",".join(inner + [f"le={b:g}"])
                    lines.append(
                        f"{name}_bucket{_prom_labels(le)} {cum}")
                le = ",".join(inner + ["le=+Inf"])
                lines.append(f"{name}_bucket{_prom_labels(le)} {count}")
                lines.append(f"{name}_sum{lab} {_finite(total)!r}")
                lines.append(f"{name}_count{lab} {count}")
        gauges = [(labels, m) for labels, m in entries
                  if isinstance(m, metrics.Gauge)]
        if gauges:
            lines.append(f"# TYPE {name}_peak gauge")
            for labels, m in gauges:
                lines.append(f"{name}_peak{_prom_labels(labels)} "
                             f"{_finite(m.peak)!r}")
    lines.extend(_hygiene_lines())
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------- handlers

class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-telemetry/1"

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        owner: "TelemetryServer" = self.server.telemetry  # type: ignore
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                monitor.record_scrape("metrics")
                self._send(200, prometheus_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                monitor.record_scrape("healthz")
                body = json.dumps({"status": "ok",
                                   "pid": os.getpid()}).encode()
                self._send(200, body, "application/json")
            elif path == "/readyz":
                monitor.record_scrape("readyz")
                ready, detail = owner.readiness()
                body = json.dumps(detail).encode()
                self._send(200 if ready else 503, body,
                           "application/json")
            elif path == "/flightrecorder":
                monitor.record_scrape("flightrecorder")
                body = json.dumps(
                    flight_recorder.dump_dict("http")).encode()
                self._send(200, body, "application/json")
            elif path == "/fleet/metrics":
                monitor.record_scrape("fleet_metrics")
                agg = owner.aggregator
                if agg is None:
                    self._send(404, b'{"error": "no fleet aggregator '
                                    b'attached"}', "application/json")
                else:
                    agg.refresh()
                    self._send(
                        200,
                        prometheus_text(agg.fleet_registry()).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/slo":
                monitor.record_scrape("slo")
                self._send(200, json.dumps(owner.slo_document()).encode(),
                           "application/json")
            elif path == "/router":
                monitor.record_scrape("router")
                router = owner.router
                if router is None:
                    self._send(404, b'{"error": "no router attached"}',
                               "application/json")
                else:
                    self._send(200, json.dumps(router.describe()).encode(),
                               "application/json")
            elif path == "/fleet/healthz":
                monitor.record_scrape("fleet_healthz")
                agg = owner.aggregator
                if agg is None:
                    self._send(404, b'{"error": "no fleet aggregator '
                                    b'attached"}', "application/json")
                else:
                    agg.refresh()
                    roll = agg.healthz()
                    # 200 even when not ready: the rollup is a
                    # DOCUMENT the router reads per-replica fields
                    # from (unlike the process /readyz probe, whose
                    # consumer is a binary load balancer check)
                    self._send(200, json.dumps(roll).encode(),
                               "application/json")
            else:
                self._send(404, b'{"error": "not found"}',
                           "application/json")
        except Exception as e:  # telemetry must never kill its server
            monitor.record_swallowed("telemetry.handler", e)
            try:
                self._send(500, b'{"error": "internal"}',
                           "application/json")
            except Exception:
                pass  # client already gone

    def log_message(self, fmt, *args):
        pass  # probes every few seconds must not spam stderr


# --------------------------------------------------------------- server

class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that can JOIN its in-flight handlers.

    The stock mixin only tracks handler threads when they are
    non-daemon (``block_on_close`` path); with ``daemon_threads = True``
    — which this server needs so a wedged scrape can't block process
    exit — ``server_close()`` joins nothing, so ``stop()`` could return
    while a handler was still mid-response and the scrape raced
    whatever teardown followed (``ServingEngine.shutdown()`` closing
    the registry's producers). Track the threads explicitly and let
    ``stop()`` wait them out with a bound."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._handler_threads: set = set()
        self._handler_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request_thread(self, request, client_address):
        t = threading.current_thread()
        with self._handler_lock:
            self._handler_threads.add(t)
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._handler_lock:
                self._handler_threads.discard(t)

    def join_handlers(self, timeout: float) -> bool:
        """Wait (bounded) for every in-flight handler to finish;
        True if none remain."""
        deadline = time.monotonic() + timeout
        while True:
            with self._handler_lock:
                live = [t for t in self._handler_threads if t.is_alive()]
            if not live:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            live[0].join(timeout=min(0.05, remaining))


class TelemetryServer:
    """The export surface. ``start()`` binds and serves on a daemon
    thread; ``attach_engine()`` (weakly) wires ``/readyz`` to a
    ServingEngine's health; ``stop()`` shuts down cleanly (idempotent).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._requested_port = int(port)
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._engine_ref = None
        self._router_ref = None
        self.aggregator = None   # FleetAggregator serving /fleet/*

    # ------------------------------------------------------ lifecycle
    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "TelemetryServer":
        if self.running:
            return self
        # opting into the export surface means opting into recording:
        # a scrapeable replica with a frozen registry answers every
        # probe with stale zeros. (enable() is idempotent and never
        # clears history; disable() later stops recording, and the
        # server keeps serving the last recorded values.)
        metrics.enable()
        self._httpd = _TrackingHTTPServer(
            (self.host, self._requested_port), _Handler)
        self._httpd.telemetry = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"telemetry:{self.port}")
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
            # drain in-flight handlers BEFORE returning: callers tear
            # down the things handlers read (engine slots, fleet
            # aggregator, registry producers) the moment stop()
            # returns, and a daemon handler thread still writing its
            # response would race that teardown
            httpd.join_handlers(timeout=5.0)
        if thread is not None:
            thread.join(timeout=5.0)

    # ------------------------------------------------------ readiness
    def attach_engine(self, engine) -> "TelemetryServer":
        """Weakly reference a ServingEngine: ``/readyz`` reflects its
        health, and a collected engine reads as not-ready (the replica
        should be rotated out, not probed forever)."""
        self._engine_ref = weakref.ref(engine)
        return self

    def attach_router(self, router) -> "TelemetryServer":
        """Weakly reference a ``serving.FleetRouter``: ``/router``
        serves its ``describe()`` document (weak for the same reason
        as the engine — a collected router must read as absent, not
        pin the whole replica table alive)."""
        self._router_ref = weakref.ref(router)
        return self

    @property
    def router(self):
        return self._router_ref() if self._router_ref is not None \
            else None

    def attach_aggregator(self, aggregator) -> "TelemetryServer":
        """Wire a ``fleet_telemetry.FleetAggregator`` to
        ``/fleet/metrics`` + ``/fleet/healthz`` — this process becomes
        the fleet's pane of glass (held strongly: the aggregator owns
        only a store client, and the fleet endpoints must outlive a
        drained local engine)."""
        self.aggregator = aggregator
        return self

    def slo_document(self) -> dict:
        """The ``/slo`` body: process-scope watchtower report, the
        attached engine's top-K request-cost table, and the fleet-scope
        evaluation when this process runs the aggregator."""
        from . import slo as slo_mod
        doc = slo_mod.report()
        engine = self._engine_ref() if self._engine_ref is not None \
            else None
        if engine is not None and hasattr(engine, "cost_table"):
            try:
                doc["top_cost"] = engine.cost_table()
            except Exception as e:
                monitor.record_swallowed("telemetry.cost_table", e)
        agg = self.aggregator
        if agg is not None and hasattr(agg, "slo_report"):
            try:
                doc["fleet"] = agg.slo_report()
            except Exception as e:
                monitor.record_swallowed("telemetry.fleet_slo", e)
        return doc

    def readiness(self) -> Tuple[bool, dict]:
        from ..distributed import resilience  # lazy: core below distributed
        if resilience.preempted():
            return False, {"ready": False, "reason": "preempted"}
        if self._engine_ref is None:
            return True, {"ready": True, "engine": None}
        engine = self._engine_ref()
        if engine is None:
            return False, {"ready": False, "reason": "engine gone"}
        health = engine.health()
        return bool(health["ready"]), health

    def __repr__(self):
        return (f"TelemetryServer(host={self.host!r}, port={self.port}, "
                f"running={self.running})")


def start_from_env(engine=None) -> Optional[TelemetryServer]:
    """The ``PADDLE_TELEMETRY_PORT`` opt-in: start a server on the
    configured port (empty/unset -> None). The ServingEngine calls this
    at construction; a training job can call it directly."""
    raw = os.environ.get("PADDLE_TELEMETRY_PORT", "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        monitor.record_swallowed(
            "telemetry.port", ValueError(f"PADDLE_TELEMETRY_PORT={raw!r}"))
        return None
    server = TelemetryServer(port=port).start()
    if engine is not None:
        server.attach_engine(engine)
    return server
