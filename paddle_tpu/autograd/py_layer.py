"""PyLayer: user-defined forward/backward (≈ paddle.autograd.PyLayer,
paddle/fluid/eager/pylayer/py_layer_node.h). The custom backward plugs into
the same GradNode tape; under jit-tracing the pair lowers to a
jax.custom_vjp-style closure."""
from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

from ..core.tensor import GradNode, Tensor, is_grad_enabled


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self.extra: dict = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    saved_tensors = saved_tensor


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(f"Use {cls.__name__}.apply(...) instead of "
                           f"constructing it")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx: PyLayerContext, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx: PyLayerContext, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (list, tuple))
        out_list = [outs] if single else list(outs)

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        if diff_inputs and is_grad_enabled():
            out_tensors = [o if isinstance(o, Tensor) else Tensor(o)
                           for o in out_list]

            def vjp_fn(ct_tree):
                cts = jax.tree_util.tree_leaves(ct_tree)
                grads = cls.backward(
                    ctx, *[Tensor(ct, stop_gradient=True) for ct in cts])
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                raw = [g.data if isinstance(g, Tensor) else g for g in grads]
                # align to diff inputs (paddle: backward returns one grad
                # per differentiable forward input, in order)
                return tuple(raw[:len(diff_inputs)])

            leaves = [t.data for t in out_tensors]
            _, treedef = jax.tree_util.tree_flatten(leaves)
            avals = [(o.shape, o.dtype) for o in leaves]
            node = GradNode(cls.__name__, vjp_fn, diff_inputs, treedef,
                            len(leaves), avals)
            for i, t in enumerate(out_tensors):
                t.stop_gradient = False
                t._node = node
                t._out_index = i
            return out_tensors[0] if single else tuple(out_tensors)
        outs2 = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]
        return outs2[0] if single else tuple(outs2)
