"""Topological backward over the eager tape, with higher-order support.

Reference analog: egr::Backward / egr::Grad
(paddle/fluid/eager/backward.cc:105,393) — a topological queue over
GradNodes with GradTensorHolder accumulation and per-tensor hooks; the
`grad()` entry restricts execution to the subgraph between outputs and
inputs and can keep building the graph (create_graph) for double grad
(exercised by fluid/tests/unittests/test_imperative_double_grad.py).

Same algorithm here over `GradNode`s whose grad function is a jax vjp
closure. For create_graph=True a node's grads are re-derived as a fresh
TAPED op (jax.vjp of the node's stored pure function, dispatched through
the normal op dispatch), so the produced gradients carry their own
GradNodes — second and higher order compose for free because jax.vjp
nests to arbitrary order.
"""
from __future__ import annotations

from collections import defaultdict, deque
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import GradNode, Tensor


def run_backward(root: Tensor, grad_tensor: Optional[Tensor] = None,
                 retain_graph: bool = False):
    """loss.backward(): accumulate into every reachable leaf's .grad."""
    if root.stop_gradient or root._node is None:
        raise RuntimeError(
            "Tensor has no grad graph (stop_gradient=True or no recorded "
            "ops); cannot call backward(). Note: backward() is an eager-mode "
            "API — inside paddle_tpu.jit-traced functions use "
            "paddle_tpu.grad / value_and_grad instead.")
    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError(
                f"grad_tensor must be given for non-scalar root "
                f"(shape {root.shape})")
        seed = jnp.ones(root.data.shape, root.dtype)
    else:
        seed = grad_tensor.data if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)
    _engine([root], [seed], targets=None, retain=retain_graph,
            create=False, accumulate_leaves=True)


def tensor_grad(outputs, inputs, grad_outputs=None,
                retain_graph: Optional[bool] = None,
                create_graph: bool = False, only_inputs: bool = True,
                allow_unused: bool = False, no_grad_vars=None):
    """paddle.grad(outputs, inputs, ...) — grads of `outputs` w.r.t.
    `inputs` without touching .grad. With create_graph=True the returned
    gradients are themselves differentiable (double grad).

    Reference: python/paddle/fluid/dygraph/base.py grad() over
    eager/backward.cc:393."""
    if not only_inputs:
        raise ValueError("only_inputs=False is not supported (matches "
                         "the reference dygraph restriction)")
    outputs = list(outputs) if isinstance(outputs, (list, tuple)) \
        else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    if not outputs or not inputs:
        raise ValueError("outputs and inputs must be non-empty")
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if len(grad_outputs) != len(outputs):
        raise ValueError(
            f"grad_outputs has {len(grad_outputs)} entries for "
            f"{len(outputs)} outputs")
    if retain_graph is None:
        retain_graph = create_graph

    seeds = []
    for o, go in zip(outputs, grad_outputs):
        if not isinstance(o, Tensor):
            raise TypeError("outputs must be Tensors")
        if go is None:
            seed = jnp.ones(o.data.shape, o.dtype)
        else:
            seed = go.data if isinstance(go, Tensor) else jnp.asarray(go)
        if create_graph and isinstance(go, Tensor):
            seeds.append(go)  # keep its graph: d(grad)/d(grad_outputs)
        else:
            seeds.append(Tensor(seed) if create_graph else seed)
    grads = _engine(outputs, seeds, targets=inputs, retain=retain_graph,
                    create=create_graph, accumulate_leaves=False,
                    no_grad_vars=no_grad_vars)
    result = []
    for t, g in zip(inputs, grads):
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs receives no gradient from "
                    "outputs (unreachable in the recorded graph); pass "
                    "allow_unused=True to get None for it")
            result.append(None)
        else:
            result.append(g)
    return result


# ----------------------------------------------------------------- engine


def _engine(outputs: Sequence[Tensor], seeds, targets, retain: bool,
            create: bool, accumulate_leaves: bool, no_grad_vars=None):
    """Shared topological executor.

    targets=None  -> full backward, leaf .grad accumulation.
    targets=[...] -> execute only nodes on a path from outputs to a
                     target; collect per-target cotangent sums.
    create=True   -> cotangents are Tensors and node grads are computed
                     by a taped dispatch (gradients stay differentiable).
    """
    target_ids = None
    if targets is not None:
        target_ids = {id(t): i for i, t in enumerate(targets)}
    stop_ids = set()
    if no_grad_vars:
        stop_ids = {id(t) for t in no_grad_vars}

    # --- reachable node set (outputs -> leaves) ------------------------
    seen = set()
    stack = []
    for o in outputs:
        if o._node is not None and o._node not in seen:
            seen.add(o._node)
            stack.append(o._node)
    while stack:
        node = stack.pop()
        for t in node.inputs:
            if id(t) in stop_ids:
                continue  # no cotangent will flow through this edge
            n = t._node
            if n is not None and n not in seen:
                seen.add(n)
                stack.append(n)

    # --- active set: nodes that can reach a target ---------------------
    if target_ids is None:
        active = seen
    else:
        # a node is active iff a target is reachable from it via input
        # edges: reverse-BFS from direct target touchers through the
        # consumer relation (iterative — tapes can be 1000s of ops deep)
        consumers: Dict[GradNode, List[GradNode]] = defaultdict(list)
        touchers = []
        for m in seen:
            direct = False
            for t in m.inputs:
                if id(t) in stop_ids:
                    continue
                if id(t) in target_ids:
                    direct = True
                elif t._node is not None:
                    consumers[t._node].append(m)
            if direct:
                touchers.append(m)
        active = set(touchers)
        bfs = deque(touchers)
        while bfs:
            n = bfs.popleft()
            for m in consumers[n]:
                if m not in active:
                    active.add(m)
                    bfs.append(m)

    if not retain and not create:
        for node in active:
            if node.vjp_fn is None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time "
                    "(use retain_graph=True on the first backward).")
    if create:
        for node in active:
            if node.closed is None:
                raise RuntimeError(
                    f"op '{node.name}' cannot re-derive a differentiable "
                    "gradient (no stored primal closure); create_graph "
                    "is unavailable for graphs containing it")

    # --- in-degrees over active nodes ----------------------------------
    indegree: Dict[GradNode, int] = defaultdict(int)
    for m in active:
        for t in m.inputs:
            if id(t) in stop_ids:
                continue
            n = t._node
            if n is not None and n in active:
                indegree[n] += 1

    grad_acc: List = [None] * (len(targets) if targets is not None else 0)

    def to_target(t: Tensor, g):
        i = target_ids[id(t)]
        cur = grad_acc[i]
        grad_acc[i] = g if cur is None else cur + g

    # --- seed the roots -------------------------------------------------
    for o, seed in zip(outputs, seeds):
        if target_ids is not None and id(o) in target_ids:
            to_target(o, seed)
        if o._node is not None and o._node in active:
            o._node.add_cotangent(o._out_index, seed)

    ready = deque([n for n in active if indegree[n] == 0])
    processed = 0
    while ready:
        node = ready.popleft()
        processed += 1
        if create:
            grads = _fire_create(node)
        elif retain:
            grads = _fire_retain(node)
        else:
            grads = node.run_vjp()
        for t, g in zip(node.inputs, grads):
            if id(t) in stop_ids:
                continue
            g = _apply_hooks(t, g, create)
            if target_ids is not None and id(t) in target_ids:
                to_target(t, g)
            n = t._node
            if n is not None and n in active:
                n.add_cotangent(t._out_index, g)
                indegree[n] -= 1
                if indegree[n] == 0:
                    ready.append(n)
            elif n is None and accumulate_leaves:
                gd = g.data if isinstance(g, Tensor) else g
                if t.grad is None:
                    t.grad = Tensor(gd, stop_gradient=True)
                else:
                    t.grad = Tensor(t.grad.data + gd, stop_gradient=True)
    if processed != len(active):
        raise RuntimeError("Cycle detected in autograd graph")

    if target_ids is None:
        return None
    out = []
    for g in grad_acc:
        if g is None:
            out.append(None)
        elif isinstance(g, Tensor):
            out.append(g if create else Tensor(g.data, stop_gradient=True))
        else:
            out.append(Tensor(g, stop_gradient=True))
    return out


def _cts_for(node: GradNode, as_tensor: bool):
    cts = []
    for i in range(node.n_outs):
        ct = node.pending.get(i)
        if ct is None:
            shape, dt = node.out_avals[i]
            ct = jnp.zeros(shape, dt)
            if as_tensor:
                ct = Tensor(ct)
        elif as_tensor and not isinstance(ct, Tensor):
            ct = Tensor(ct)
        elif not as_tensor and isinstance(ct, Tensor):
            ct = ct.data
        cts.append(ct)
    node.pending.clear()
    return cts


def _fire_retain(node: GradNode):
    cts = _cts_for(node, as_tensor=False)
    ct_tree = jax.tree_util.tree_unflatten(node.out_treedef, cts)
    return node.vjp_fn(ct_tree)


def _fire_create(node: GradNode):
    """Re-derive this node's gradients as a TAPED op so they are
    themselves differentiable. jax.vjp re-runs the forward — double
    backward trades compute for composability, like the reference
    re-running grad-op construction under create_graph."""
    from ..core.tensor import dispatch
    cts = _cts_for(node, as_tensor=True)
    closed, treedef, n_in = node.closed, node.out_treedef, len(node.inputs)

    def grad_impl(*vals):
        primals, ct_leaves = vals[:n_in], vals[n_in:]
        ct_tree = jax.tree_util.tree_unflatten(treedef, ct_leaves)
        _, vjp_fn = jax.vjp(closed, *primals)
        return tuple(vjp_fn(ct_tree))

    out = dispatch("grad::" + node.name, grad_impl,
                   tuple(node.inputs) + tuple(cts), {})
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _apply_hooks(t: Tensor, g, create: bool):
    if not t._hooks:
        return g
    gt = g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True)
    for hook in t._hooks:
        res = hook(gt)
        if res is not None:
            gt = res if isinstance(res, Tensor) else Tensor(res)
    return gt if create else gt.data
