"""Topological backward over the eager tape.

Reference analog: egr::Backward / RunBackward
(paddle/fluid/eager/backward.cc:105,393) — a topological queue over GradNodes
with GradTensorHolder accumulation and per-tensor hooks. Same algorithm here,
over `GradNode`s whose grad function is a jax vjp closure.
"""
from __future__ import annotations

from collections import defaultdict, deque
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import GradNode, Tensor


def run_backward(root: Tensor, grad_tensor: Optional[Tensor] = None,
                 retain_graph: bool = False):
    if root.stop_gradient or root._node is None:
        raise RuntimeError(
            "Tensor has no grad graph (stop_gradient=True or no recorded "
            "ops); cannot call backward(). Note: backward() is an eager-mode "
            "API — inside paddle_tpu.jit-traced functions use "
            "paddle_tpu.grad / value_and_grad instead.")
    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError(
                f"grad_tensor must be given for non-scalar root "
                f"(shape {root.shape})")
        seed_ct = jnp.ones(root.data.shape, root.dtype)
    else:
        seed_ct = grad_tensor.data if isinstance(grad_tensor, Tensor) \
            else jnp.asarray(grad_tensor)

    # --- collect reachable graph; count in-degrees (uses of each node) -----
    indegree: dict[GradNode, int] = defaultdict(int)
    seen = set()
    stack = [root._node]
    seen.add(root._node)
    while stack:
        node = stack.pop()
        for t in node.inputs:
            n = t._node
            if n is not None:
                indegree[n] += 1
                if n not in seen:
                    seen.add(n)
                    stack.append(n)

    if not retain_graph:
        for node in seen:
            if node.vjp_fn is None:
                raise RuntimeError(
                    "Trying to backward through the graph a second time "
                    "(use retain_graph=True on the first backward).")

    root._node.add_cotangent(root._out_index, seed_ct)

    ready = deque([n for n in seen if indegree[n] == 0])
    processed = 0
    while ready:
        node = ready.popleft()
        processed += 1
        if retain_graph:
            vjp_fn, avals = node.vjp_fn, node.out_avals
            grads = _run_with_retain(node)
        else:
            grads = node.run_vjp()
        for t, g in zip(node.inputs, grads):
            g = _apply_hooks(t, g)
            n = t._node
            if n is None:
                # leaf: accumulate into .grad
                if t.grad is None:
                    t.grad = Tensor(g, stop_gradient=True)
                else:
                    t.grad = Tensor(t.grad.data + g, stop_gradient=True)
            else:
                n.add_cotangent(t._out_index, g)
                indegree[n] -= 1
                if indegree[n] == 0:
                    ready.append(n)
    if processed != len(seen):
        raise RuntimeError("Cycle detected in autograd graph")


def _run_with_retain(node: GradNode):
    import jax
    cts = []
    for i in range(node.n_outs):
        ct = node.pending.get(i)
        if ct is None:
            shape, dt = node.out_avals[i]
            ct = jnp.zeros(shape, dt)
        cts.append(ct)
    ct_tree = jax.tree_util.tree_unflatten(node.out_treedef, cts)
    grads = node.vjp_fn(ct_tree)
    node.pending.clear()
    return grads


def _apply_hooks(t: Tensor, g):
    if not t._hooks:
        return g
    gt = Tensor(g, stop_gradient=True)
    for hook in t._hooks:
        res = hook(gt)
        if res is not None:
            gt = res if isinstance(res, Tensor) else Tensor(res)
    return gt.data
