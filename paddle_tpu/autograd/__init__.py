from ..core.tensor import enable_grad, is_grad_enabled, no_grad  # noqa: F401
from .backward_engine import run_backward, tensor_grad  # noqa: F401
from .backward_engine import tensor_grad as grad  # noqa: F401
from .py_layer import PyLayer  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward analog."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        run_backward(t, g, retain_graph)
