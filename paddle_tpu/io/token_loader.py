"""TokenLoader: high-throughput LM pretraining input pipeline backed by
the native C++ feeder (paddle_tpu/native/token_feeder.cc — the
data_feed.cc / DataLoader-worker analog), with a pure-Python fallback.

Feeds fixed [batch, seq_len+1] int32 windows from a flat binary token
corpus; shuffled per epoch, sharded across dp ranks. Iteration yields
(input_ids, labels) where labels are input_ids shifted by one token —
pair them with a per-position LM loss (for GPTForCausalLM.loss, which
shifts internally, pass the same window as both arguments instead).
"""
from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Tuple

import numpy as np


class TokenLoader:
    def __init__(self, path: str, seq_len: int, batch_size: int,
                 num_workers: int = 2, seed: int = 0,
                 prefetch: int = 4, rank: int = 0, world_size: int = 1,
                 drop_last: bool = True, use_native: Optional[bool] = None):
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.num_workers = max(num_workers, 1)
        self.seed = seed
        self.prefetch = max(prefetch, 2)
        self.rank = rank
        self.world_size = max(world_size, 1)
        self.drop_last = drop_last

        from .. import native
        self._lib = native.lib() if use_native in (None, True) else None
        if use_native is True and self._lib is None:
            raise RuntimeError("native feeder requested but unavailable")
        self._handle = None
        self._epoch = 0
        if self._lib is not None:
            self._handle = self._lib.pt_feeder_create(
                path.encode(), seq_len, batch_size, self.num_workers,
                seed, self.prefetch, rank, self.world_size,
                1 if drop_last else 0)
            if not self._handle:
                raise RuntimeError(f"cannot map token file {path}")
        else:
            self._tokens = np.fromfile(path, dtype=np.int32)

    # ------------------------------------------------------------- sizing
    @property
    def num_batches(self) -> int:
        if self._handle:
            return self._lib.pt_feeder_num_batches(self._handle)
        total = self._tokens.size // (self.seq_len + 1)
        mine = len(range(self.rank, total, self.world_size))
        return mine // self.batch_size if self.drop_last else \
            -(-mine // self.batch_size)

    def __len__(self) -> int:
        return self.num_batches

    # ---------------------------------------------------------- iteration
    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        if self._handle:
            if self._epoch > 0:
                self._lib.pt_feeder_next_epoch(self._handle)
            self._epoch += 1
            stride = self.seq_len + 1
            while True:
                out = np.empty((self.batch_size, stride), dtype=np.int32)
                ok = self._lib.pt_feeder_next(
                    self._handle,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
                if not ok:
                    return
                yield out[:, :-1].copy(), out[:, 1:].astype(np.int64)
        else:
            yield from self._py_iter()

    def _py_iter(self):
        stride = self.seq_len + 1
        total = self._tokens.size // stride
        rng = np.random.RandomState(
            (self.seed + self._epoch) % (2 ** 31))
        self._epoch += 1
        order = rng.permutation(total)[self.rank::self.world_size]
        nb = self.num_batches
        for b in range(nb):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size:  # wrap the final partial batch
                idx = np.concatenate(
                    [idx, order[:self.batch_size - len(idx)]])
            rows = np.stack([self._tokens[i * stride:(i + 1) * stride]
                             for i in idx])
            yield rows[:, :-1].copy(), rows[:, 1:].astype(np.int64)

    def __del__(self):
        h = getattr(self, "_handle", None)
        if h:
            try:
                self._lib.pt_feeder_destroy(h)
            except Exception:
                pass
            self._handle = None
