"""DataLoader (≈ python/paddle/fluid/reader.py:322 DataLoader;
multi-process iterator fluid/dataloader/dataloader_iter.py:381).

TPU-first shape: the loader produces HOST numpy batches and prefetches
device transfers asynchronously (double buffering) so input pipeline
overlaps with device compute — the role the reference's shared-memory
worker queues + pin_memory play for GPUs. num_workers=0 prefetches on
a thread (numpy collation releases the GIL); num_workers>0 fans sample
loading + collation out to forked worker PROCESSES (the reference's
_DataLoaderIterMultiProcess, dataloader_iter.py:381) for Python-bound
transforms, with order-preserving handoff.
"""
from __future__ import annotations

import collections
import multiprocessing as mp
import queue
import threading
from typing import Callable, Optional

import jax
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def _batch_stats(batch):
    """(nsamples, nbytes) of a collated batch — leading dim of the first
    array leaf, total array bytes. Only walked while the monitor is on."""
    nbytes = 0
    nsamples = 0
    for leaf in jax.tree_util.tree_leaves(
            batch, is_leaf=lambda x: isinstance(x, Tensor)):
        arr = leaf.data if isinstance(leaf, Tensor) else leaf
        if hasattr(arr, "nbytes"):
            nbytes += arr.nbytes
            if not nsamples and getattr(arr, "shape", ()):
                nsamples = int(arr.shape[0])
    return nsamples, nbytes


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b.data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


# ---------------------------------------------------------------- workers
# fork-context pool: workers inherit the dataset/collate via these
# globals set in the initializer (same shared-state shape as the
# reference's worker loop, minus the shared-memory tensor plumbing —
# numpy batches pickle efficiently)
_WORKER_STATE: dict = {}


def _worker_init(dataset, collate_fn, user_init_fn, id_counter,
                 num_workers):
    _WORKER_STATE["ds"] = dataset
    _WORKER_STATE["collate"] = collate_fn
    with id_counter.get_lock():
        # modulo: Pool respawns a crashed worker re-running this init;
        # ids must stay in [0, num_workers)
        worker_id = id_counter.value % num_workers
        id_counter.value += 1
    global _WORKER_INFO
    # deterministic per-worker seed (reference contract: base_seed +
    # worker_id, reproducible augmentation across runs)
    from ..core import flags as _flags
    base_seed = int(_flags.get_flag("seed") or 0)
    _WORKER_INFO = WorkerInfo(worker_id, num_workers,
                              base_seed + worker_id, dataset)
    if user_init_fn is not None:
        user_init_fn(worker_id)


def _worker_fetch(indices):
    ds = _WORKER_STATE["ds"]
    return _WORKER_STATE["collate"]([ds[i] for i in indices])


class _PrefetchIterator:
    def __init__(self, loader: "DataLoader"):
        self._loader = loader
        self._index_iter = iter(loader.batch_sampler) \
            if loader.batch_sampler is not None else None
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=max(2, loader.prefetch_factor))
        self._done = object()
        self._err = None
        self._stopped = False
        self._pool = None
        if loader.num_workers > 0 and self._index_iter is not None:
            # fork on the CONSUMER thread, before the producer thread
            # exists and before this iterator touches the device —
            # forking from a helper thread while JAX dispatch threads
            # hold locks is how the classic post-fork deadlock happens
            ctx = mp.get_context("fork")
            counter = ctx.Value("i", 0)
            self._pool = ctx.Pool(
                loader.num_workers, initializer=_worker_init,
                initargs=(loader.dataset, loader.collate_fn,
                          loader.worker_init_fn, counter,
                          loader.num_workers))
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _fetch_batch(self, indices):
        ds = self._loader.dataset
        samples = [ds[i] for i in indices]
        return self._loader.collate_fn(samples)

    def _produce(self):
        try:
            if isinstance(self._loader.dataset, IterableDataset):
                batch = []
                for item in self._loader.dataset:
                    batch.append(item)
                    if len(batch) == self._loader.batch_size:
                        self._queue.put(self._to_device(
                            self._loader.collate_fn(batch)))
                        batch = []
                if batch and not self._loader.drop_last:
                    self._queue.put(self._to_device(
                        self._loader.collate_fn(batch)))
            elif self._pool is not None:
                # imap preserves batch order across workers
                for batch in self._pool.imap(_worker_fetch,
                                             self._index_iter):
                    if not self._put(self._to_device(batch)):
                        return  # consumer abandoned the iterator
            else:
                for indices in self._index_iter:
                    if not self._put(self._to_device(
                            self._fetch_batch(indices))):
                        return
        except Exception as e:  # surface in consumer thread
            self._err = e
        finally:
            self._put(self._done)
            self._shutdown_pool()

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer closed us, so an
        abandoned iterator never leaves this thread (and the worker
        pool) blocked forever."""
        while not self._stopped:
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _shutdown_pool(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.terminate()
            pool.join()

    def close(self):
        """Stop the producer and reap worker processes."""
        self._stopped = True
        try:  # unblock a producer stuck in put()
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        self._shutdown_pool()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _to_device(self, batch):
        # async host->device: device_put returns immediately, transfer
        # overlaps with compute on the prior batch
        def put(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                if x.dtype == np.int64 and self._loader.keep_int64 is False:
                    x = x.astype(np.int32)
                return Tensor(jax.device_put(x))
            return x

        return jax.tree_util.tree_map(put, batch)

    def __next__(self):
        item = self._queue.get()
        if item is self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        if monitor.enabled:
            monitor.record_dataloader_batch(*_batch_stats(item))
        return item

    def __iter__(self):
        return self


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None, batch_size=1,
                 shuffle: bool = False, drop_last: bool = False,
                 collate_fn: Optional[Callable] = None, num_workers: int = 0,
                 use_buffer_reader: bool = True, prefetch_factor: int = 2,
                 use_shared_memory: bool = False, timeout=0,
                 worker_init_fn=None, keep_int64: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.keep_int64 = keep_int64
        self.num_workers = int(num_workers)
        self.worker_init_fn = worker_init_fn
        if self.num_workers > 0 and isinstance(dataset, IterableDataset):
            raise ValueError(
                "num_workers > 0 requires a map-style Dataset "
                "(IterableDataset iteration is inherently sequential)")
        if self.num_workers > 0 and \
                "fork" not in mp.get_all_start_methods():
            raise ValueError(
                "num_workers > 0 needs the 'fork' start method "
                "(unavailable on this platform); use num_workers=0")
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __iter__(self):
        return _PrefetchIterator(self)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)


class WorkerInfo:
    """Info about the current DataLoader worker (reference
    fluid/dataloader/worker.py WorkerInfo)."""

    def __init__(self, id: int, num_workers: int, seed: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_WORKER_INFO = None


def get_worker_info():
    """In a worker process: that worker's WorkerInfo; None in the main
    process (reference io.get_worker_info)."""
    return _WORKER_INFO
