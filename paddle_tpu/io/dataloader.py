"""DataLoader (≈ python/paddle/fluid/reader.py:322 DataLoader;
multi-process iterator fluid/dataloader/dataloader_iter.py:381).

TPU-first shape: the loader produces HOST numpy batches and prefetches
device transfers asynchronously (double buffering) so input pipeline
overlaps with device compute — the role the reference's shared-memory
worker queues + pin_memory play for GPUs. num_workers=0 prefetches on
a thread (numpy collation releases the GIL); num_workers>0 fans sample
loading + collation out to forked worker PROCESSES (the reference's
_DataLoaderIterMultiProcess, dataloader_iter.py:381) for Python-bound
transforms, with order-preserving handoff.

Fault domain (the reference supervises its workers the same way —
fluid/dataloader/dataloader_iter.py watches worker exit and re-raises
instead of hanging): the worker pool here is SUPERVISED. Every batch is
dispatched with an explicit batch index; the supervisor thread polls
worker liveness while waiting for results, respawns dead workers within
a bounded budget (re-dispatching their in-flight batches, so the batch
stream stays identical), enforces a per-fetch deadline that surfaces a
wedged worker as ``resilience.WatchdogTimeout`` (with a full stack
dump) instead of stalling the pod, and propagates worker exceptions to
the consumer with the failing sample index attached. Opt-in
``skip_bad_samples`` quarantines samples that raise or contain
non-finite data (dropped from the batch, counted in
``io.sample.quarantined``, listed on ``loader.quarantined``).

Exact mid-epoch resume: ``DataLoader.state_dict()`` captures the batch
cursor of the active iterator plus the sampler's epoch/RNG state (the
t5x/Grain checkpointable-input-iterator contract);
``load_state_dict()`` arms the next ``__iter__`` to restore the sampler
and fast-forward the index stream, so a preempted job replays the exact
remaining batch sequence.
"""
from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import time
import traceback
import weakref
from typing import Callable, Optional

import jax
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def _batch_stats(batch):
    """(nsamples, nbytes) of a collated batch — leading dim of the first
    array leaf, total array bytes. Only walked while the monitor is on."""
    nbytes = 0
    nsamples = 0
    for leaf in jax.tree_util.tree_leaves(
            batch, is_leaf=lambda x: isinstance(x, Tensor)):
        arr = leaf.data if isinstance(leaf, Tensor) else leaf
        if hasattr(arr, "nbytes"):
            nbytes += arr.nbytes
            if not nsamples and getattr(arr, "shape", ()):
                nsamples = int(arr.shape[0])
    return nsamples, nbytes


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b.data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


class DataLoaderWorkerError(RuntimeError):
    """A DataLoader worker failed: a sample raised, collation raised, or
    the worker process died past its respawn budget. Carries the worker
    id and (when known) the exact sample index that failed."""

    def __init__(self, message, worker_id=None, sample_index=None,
                 batch_indices=None):
        super().__init__(message)
        self.worker_id = worker_id
        self.sample_index = sample_index
        self.batch_indices = list(batch_indices) if batch_indices else []


def _sample_finite(sample) -> bool:
    """True when every float array/scalar leaf of a sample is finite."""
    def walk(x):
        if isinstance(x, Tensor):
            x = x.data
        if isinstance(x, dict):
            return all(walk(v) for v in x.values())
        if isinstance(x, (list, tuple)):
            return all(walk(v) for v in x)
        arr = np.asarray(x) if not isinstance(x, np.ndarray) else x
        if np.issubdtype(arr.dtype, np.floating) or \
                np.issubdtype(arr.dtype, np.complexfloating):
            return bool(np.isfinite(arr).all())
        return True

    try:
        return walk(sample)
    except (TypeError, ValueError):
        return True  # non-numeric sample: not this check's business


def _format_exc(e: BaseException) -> str:
    return "".join(traceback.format_exception(type(e), e, e.__traceback__))


def _fetch_one(dataset, collate_fn, batch_idx, worker_id, indices,
               quarantine):
    """Fetch + collate one index batch; the one envelope format both the
    worker processes and the in-process paths produce:

    ``("batch", batch_idx, worker_id, batch_or_None, dropped)`` where
    ``dropped`` is ``[(sample_index, reason), ...]`` (quarantine mode),
    or ``("error", batch_idx, worker_id, indices, sample_index, tb)``
    with the exact failing sample attributed."""
    samples, dropped = [], []
    for i in indices:
        try:
            s = dataset[i]
        except Exception as e:
            if quarantine:
                dropped.append((int(i), f"{type(e).__name__}: {e}"))
                continue
            return ("error", batch_idx, worker_id, list(indices), int(i),
                    _format_exc(e))
        if quarantine and not _sample_finite(s):
            dropped.append((int(i), "non-finite sample"))
            continue
        samples.append(s)
    if not samples:
        return ("batch", batch_idx, worker_id, None, dropped)
    try:
        batch = collate_fn(samples)
    except Exception as e:
        return ("error", batch_idx, worker_id, list(indices), None,
                _format_exc(e))
    return ("batch", batch_idx, worker_id, batch, dropped)


# ---------------------------------------------------------------- workers

def _worker_loop(dataset, collate_fn, user_init_fn, worker_id, num_workers,
                 index_queue, result_queue, quarantine, base_seed):
    """Worker-process main: pull (batch_idx, indices) jobs until the
    None sentinel. Errors travel back as envelopes, never tracebacks to
    a dead pipe (the reference's _worker_loop contract)."""
    global _WORKER_INFO
    # a worker forked while the parent runs under GracefulShutdown
    # inherits its flag-only SIGTERM handler — which would make this
    # process unkillable by Process.terminate() and hang the parent's
    # exit-time join. Workers answer to the supervisor, not to signals:
    # restore the default dispositions.
    import signal as _signal
    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
    _signal.signal(_signal.SIGINT, _signal.SIG_DFL)
    # deterministic per-worker seed (reference contract: base_seed +
    # worker_id, reproducible augmentation across runs and respawns)
    _WORKER_INFO = WorkerInfo(worker_id, num_workers,
                              base_seed + worker_id, dataset)
    np.random.seed((base_seed + worker_id) % (2 ** 32))
    try:
        if user_init_fn is not None:
            user_init_fn(worker_id)
    except Exception as e:
        result_queue.put(("error", -1, worker_id, [], None, _format_exc(e)))
        return
    while True:
        try:
            job = index_queue.get()
        except (EOFError, OSError):
            return
        if job is None:
            return
        batch_idx, indices = job
        try:
            result_queue.put(_fetch_one(dataset, collate_fn, batch_idx,
                                        worker_id, indices, quarantine))
        except (EOFError, OSError, BrokenPipeError):
            return  # parent gone: nothing left to report to


class _PrefetchIterator:
    def __init__(self, loader: "DataLoader", skip_batches: int = 0):
        self._loader = loader
        bs = loader.batch_sampler
        # sampler state snapshot BEFORE iter() (which advances the
        # sampler's epoch) — this is what state_dict() hands a resume
        self._sampler_state = bs.state_dict() \
            if bs is not None and hasattr(bs, "state_dict") else {}
        self._index_iter = iter(bs) if bs is not None else None
        self._skip = int(skip_batches)
        self._cursor = self._skip  # index batches consumed (consumer view)
        if self._skip and self._index_iter is not None:
            # mid-epoch resume: fast-forward at the INDEX level — no
            # sample fetch, no collation, just the sampler replaying
            for _ in range(self._skip):
                if next(self._index_iter, None) is None:
                    break
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=max(2, loader.prefetch_factor))
        self._done = object()
        self._err = None
        self._stopped = False
        self._closed = False
        self._exhausted = False
        self.quarantined: list = []
        # ------------------------------------------- supervised pool
        self._pool = None
        self._ctx = None
        self._workers: list = []
        self._index_queues: list = []
        self._result_queue = None
        self._in_flight: dict = {}  # batch_idx -> (wid, indices)
        # wid -> monotonic time the worker last made progress while
        # holding in-flight work (dispatch into an idle worker, or its
        # most recent result): the per-fetch deadline is measured from
        # here, so queueing behind other batches never counts against it
        self._busy_since: dict = {}
        self._respawns_left = int(loader.worker_respawn_limit)
        self._fetch_timeout = loader._fetch_timeout()
        if loader.num_workers > 0 and self._index_iter is not None:
            # fork on the CONSUMER thread, before the producer thread
            # exists and before this iterator touches the device —
            # forking from a helper thread while JAX dispatch threads
            # hold locks is how the classic post-fork deadlock happens
            self._spawn_pool()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    # ------------------------------------------------------- pool plumbing
    def _spawn_pool(self):
        loader = self._loader
        ctx = mp.get_context("fork")
        self._ctx = ctx
        self._result_queue = ctx.Queue()
        from ..core import flags as _flags
        self._base_seed = int(_flags.get_flag("seed") or 0)
        for wid in range(loader.num_workers):
            self._start_worker(wid)
        # the live-pool handle close() nulls out (and tests assert on)
        self._pool = self._workers

    def _start_worker(self, wid: int):
        loader = self._loader
        q = self._ctx.Queue()
        p = self._ctx.Process(
            target=_worker_loop,
            args=(loader.dataset, loader.collate_fn, loader.worker_init_fn,
                  wid, loader.num_workers, q, self._result_queue,
                  loader.skip_bad_samples, self._base_seed),
            daemon=True)
        p.start()
        if wid < len(self._workers):
            old_q = self._index_queues[wid]
            self._workers[wid] = p
            self._index_queues[wid] = q
            try:  # the dead worker's queue: nothing reads it anymore
                old_q.cancel_join_thread()
                old_q.close()
            except (OSError, ValueError):
                pass
        else:
            self._workers.append(p)
            self._index_queues.append(q)

    def _dispatch(self, batch_idx: int, wid: int, indices):
        self._in_flight[batch_idx] = (wid, list(indices))
        self._busy_since.setdefault(wid, time.monotonic())
        self._index_queues[wid].put((batch_idx, list(indices)))

    def _note_progress(self, wid):
        """A result arrived from ``wid``: restart its fetch clock (or
        clear it when the worker went idle)."""
        if wid is None:
            return
        if any(f[0] == wid for f in self._in_flight.values()):
            self._busy_since[wid] = time.monotonic()
        else:
            self._busy_since.pop(wid, None)

    def _check_workers(self):
        """Liveness + per-fetch deadline sweep, run whenever the result
        wait comes up empty. Dead worker -> respawn (bounded) and
        re-dispatch its in-flight batches; wedged worker (no progress on
        its current fetch past the deadline) -> stack dump +
        WatchdogTimeout, the hang surfaced instead of stalling the pod."""
        for wid, p in enumerate(self._workers):
            if p is None or p.is_alive():
                continue
            monitor.record_worker_death(wid)
            if self._respawns_left <= 0:
                raise DataLoaderWorkerError(
                    f"DataLoader worker {wid} died (exitcode "
                    f"{p.exitcode}) and the respawn budget is exhausted",
                    worker_id=wid)
            self._respawns_left -= 1
            lost = sorted((b, f) for b, f in self._in_flight.items()
                          if f[0] == wid)
            # fork happens on the supervisor thread here — acceptable
            # because workers only run dataset/collate code, never the
            # jax dispatch machinery whose locks make forking from
            # threads dangerous
            self._start_worker(wid)
            monitor.record_worker_respawn(wid)
            self._busy_since.pop(wid, None)
            for b, (_, idxs) in lost:
                self._dispatch(b, wid, idxs)
        if not self._fetch_timeout:
            return
        now = time.monotonic()
        for wid, t0 in list(self._busy_since.items()):
            if now - t0 <= self._fetch_timeout:
                continue
            # the worker has held in-flight work without producing a
            # single result for a full deadline: wedged (a healthy
            # worker finishes each fetch well inside it; batches merely
            # QUEUED behind others never start this clock)
            owned = sorted(b for b, f in self._in_flight.items()
                           if f[0] == wid)
            idxs = self._in_flight[owned[0]][1] if owned else []
            from ..distributed import resilience
            resilience.dump_stacks("io.fetch", self._fetch_timeout)
            monitor.record_watchdog_timeout("io.fetch")
            raise resilience.WatchdogTimeout(
                f"DataLoader fetch of batch "
                f"{owned[0] if owned else '?'} (worker {wid}, samples "
                f"{idxs[:8]}{'...' if len(idxs) > 8 else ''}) exceeded "
                f"{self._fetch_timeout:.1f}s — worker wedged")

    def _note_quarantined(self, dropped):
        if not dropped:
            return
        self.quarantined.extend(dropped)
        # mirrored on the loader so the record outlives the iterator
        self._loader._quarantined.extend(dropped)
        monitor.record_sample_quarantined(len(dropped))

    # ------------------------------------------------------------- produce
    def _produce(self):
        try:
            if isinstance(self._loader.dataset, IterableDataset):
                self._produce_iterable()
            elif self._workers:
                self._produce_mp()
            else:
                self._produce_sp()
        except Exception as e:  # surface in consumer thread
            self._err = e
        finally:
            self._put(self._done)
            self._shutdown_pool()

    def _produce_iterable(self):
        loader = self._loader
        batch, batch_idx, pos = [], 0, -1
        quarantine = loader.skip_bad_samples

        def emit(b, idx):
            if idx < self._skip:
                return True  # resume fast-forward: count, don't collate
            return self._put((idx, self._to_device(loader.collate_fn(b))))

        for item in loader.dataset:
            pos += 1
            if quarantine and not _sample_finite(item):
                self._note_quarantined([(pos, "non-finite sample")])
                continue
            batch.append(item)
            if len(batch) == loader.batch_size:
                if not emit(batch, batch_idx):
                    return
                batch_idx += 1
                batch = []
        if batch and not loader.drop_last:
            emit(batch, batch_idx)

    def _produce_sp(self):
        loader = self._loader
        batch_idx = self._skip
        for indices in self._index_iter:
            env = _fetch_one(loader.dataset, loader.collate_fn, batch_idx,
                             None, indices, loader.skip_bad_samples)
            if env[0] == "error":
                _, _, _, idxs, sample_i, tb = env
                raise DataLoaderWorkerError(
                    f"DataLoader sample fetch failed"
                    + (f" at sample index {sample_i}"
                       if sample_i is not None else "")
                    + f":\n{tb}", sample_index=sample_i,
                    batch_indices=idxs)
            _, _, _, batch, dropped = env
            self._note_quarantined(dropped)
            if batch is not None:
                if not self._put((batch_idx, self._to_device(batch))):
                    return
            batch_idx += 1

    def _produce_mp(self):
        loader = self._loader
        max_outstanding = max(2, loader.prefetch_factor) * loader.num_workers
        buffer: dict = {}
        next_emit = self._skip
        next_dispatch = self._skip
        exhausted = False
        rr = 0
        while not self._stopped:
            while not exhausted and len(self._in_flight) < max_outstanding:
                indices = next(self._index_iter, None)
                if indices is None:
                    exhausted = True
                    break
                self._dispatch(next_dispatch, rr % loader.num_workers,
                               indices)
                next_dispatch += 1
                rr += 1
            if exhausted and not self._in_flight:
                return
            try:
                env = self._result_queue.get(timeout=0.1)
            except queue.Empty:
                self._check_workers()
                continue
            if env[0] == "error":
                _, _, wid, idxs, sample_i, tb = env
                raise DataLoaderWorkerError(
                    f"DataLoader worker {wid} failed"
                    + (f" at sample index {sample_i}"
                       if sample_i is not None else "")
                    + f":\n{tb}", worker_id=wid, sample_index=sample_i,
                    batch_indices=idxs)
            _, batch_idx, wid, batch, dropped = env
            if batch_idx not in self._in_flight:
                self._note_progress(wid)
                continue  # duplicate after a respawn re-dispatch
            del self._in_flight[batch_idx]
            self._note_progress(wid)
            self._note_quarantined(dropped)
            buffer[batch_idx] = batch
            # order-preserving release (imap semantics, but index-driven
            # so a respawned worker's re-computed batch slots back in)
            while next_emit in buffer:
                b = buffer.pop(next_emit)
                if b is not None:
                    if not self._put((next_emit, self._to_device(b))):
                        return
                next_emit += 1

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer closed us, so an
        abandoned iterator never leaves this thread (and the worker
        pool) blocked forever."""
        while not self._stopped:
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------ teardown
    def _shutdown_pool(self):
        workers, self._workers = self._workers, []
        index_queues, self._index_queues = self._index_queues, []
        self._pool = None
        self._in_flight.clear()
        if not workers:
            return
        for q in index_queues:
            try:
                q.put_nowait(None)  # graceful-exit sentinel
            except (OSError, ValueError, queue.Full):
                pass
        for p in workers:
            if p is not None:
                p.join(timeout=0.5)
        for p in workers:
            if p is not None and p.is_alive():
                # SIGKILL, not SIGTERM: a wedged (SIGSTOPped) worker
                # never handles SIGTERM, and KILL works on stopped
                # processes too
                p.kill()
                p.join(timeout=5.0)
        for q in index_queues + [self._result_queue]:
            if q is None:
                continue
            try:
                q.cancel_join_thread()
                q.close()
            except (OSError, ValueError):
                pass
        self._result_queue = None

    def close(self):
        """Stop the producer and reap worker processes. Idempotent, and
        called automatically on every consumer-side exit path
        (StopIteration, propagated worker error, __del__), so an aborted
        epoch can never leak the pool."""
        if self._closed:
            return
        self._closed = True
        self._stopped = True
        try:  # unblock a producer stuck in put()
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        self._shutdown_pool()
        try:
            # wake a consumer blocked in __next__ on another thread so
            # a cross-thread close can never strand it
            self._queue.put_nowait(self._done)
        except queue.Full:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------- consume
    def _to_device(self, batch):
        # async host->device: device_put returns immediately, transfer
        # overlaps with compute on the prior batch. With batch
        # shardings installed (DataLoader.set_batch_shardings, usually
        # the train step's batch_sharding_for) each leaf is placed
        # COMMITTED on its target sharding, so the consumer's own
        # _shard_batch re-placement becomes a counted no-op; leaves
        # already resident on their target are never re-placed
        # (idempotent — io.host2device.{placed,skipped,bytes}).
        from .device_prefetch import place_batch
        loader = self._loader

        def convert(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                if x.dtype == np.int64 and loader.keep_int64 is False:
                    x = x.astype(np.int32)
            return x

        batch = jax.tree_util.tree_map(
            convert, batch, is_leaf=lambda t: isinstance(t, Tensor))
        return place_batch(batch, loader._batch_shardings)

    def __next__(self):
        if self._exhausted:
            if self._err is not None:
                raise self._err
            raise StopIteration
        if self._closed:
            # closed without being consumed to the end — most likely a
            # second iter() on the same DataLoader invalidated this one
            # (one active iterator per loader); fail loudly rather than
            # block forever on a queue nothing fills
            raise RuntimeError(
                "DataLoader iterator is closed (creating a new iterator "
                "from the same DataLoader closes the previous one)")
        item = self._queue.get()
        if item is self._done:
            self._exhausted = True
            if self._err is not None:
                self.close()  # error path must reap the pool too
                raise self._err
            self.close()
            raise StopIteration
        batch_idx, batch = item
        self._cursor = batch_idx + 1
        if monitor.enabled:
            monitor.record_dataloader_batch(*_batch_stats(batch))
        return batch

    def __iter__(self):
        return self


def _state_scalar(v):
    """Coerce a checkpoint-restored leaf (Tensor / 0-d array / scalar)
    back to the plain python number sampler state is made of."""
    v = getattr(v, "data", v)
    arr = np.asarray(v)
    return arr.item() if arr.shape == () else arr.tolist()


def _coerce_state(node):
    if isinstance(node, dict):
        return {k: _coerce_state(v) for k, v in node.items()}
    if node is None:
        return None
    return _state_scalar(node)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None, batch_size=1,
                 shuffle: bool = False, drop_last: bool = False,
                 collate_fn: Optional[Callable] = None, num_workers: int = 0,
                 use_buffer_reader: bool = True, prefetch_factor: int = 2,
                 use_shared_memory: bool = False, timeout=0,
                 worker_init_fn=None, keep_int64: bool = True,
                 worker_respawn_limit: int = 3,
                 skip_bad_samples: bool = False,
                 batch_shardings=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.keep_int64 = keep_int64
        self.num_workers = int(num_workers)
        self.worker_init_fn = worker_init_fn
        # per-fetch deadline (seconds; 0 = PADDLE_WATCHDOG_DATALOADER_S
        # env, unset = no deadline) — a wedged worker surfaces as
        # WatchdogTimeout instead of stalling the whole pod
        self.timeout = float(timeout or 0)
        self.worker_respawn_limit = int(worker_respawn_limit)
        self.skip_bad_samples = bool(skip_bad_samples)
        self._batch_shardings = batch_shardings
        self._latest_iter = None
        self._resume_state: Optional[dict] = None
        self._quarantined: list = []
        if self.num_workers > 0 and isinstance(dataset, IterableDataset):
            raise ValueError(
                "num_workers > 0 requires a map-style Dataset "
                "(IterableDataset iteration is inherently sequential)")
        if self.num_workers > 0 and \
                "fork" not in mp.get_all_start_methods():
            raise ValueError(
                "num_workers > 0 needs the 'fork' start method "
                "(unavailable on this platform); use num_workers=0")
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def set_batch_shardings(self, shardings) -> "DataLoader":
        """Install per-leaf device placement targets for the prefetch
        thread: ``None`` (default device, uncommitted), one Sharding
        for every leaf, or a callable ``leaf -> Sharding`` — typically
        the train step's ``batch_sharding_for``, so batches arrive
        already committed on the step's input shardings and its own
        ``_shard_batch`` becomes a counted no-op."""
        self._batch_shardings = shardings
        return self

    def _fetch_timeout(self) -> Optional[float]:
        if self.timeout > 0:
            return self.timeout
        from ..distributed.resilience import env_timeout
        return env_timeout("PADDLE_WATCHDOG_DATALOADER_S")

    def __iter__(self):
        # an abandoned previous epoch (break mid-iteration) must not
        # keep its worker pool alive behind the new one
        prev = self._active_iter()
        if prev is not None and not prev._closed:
            prev.close()
        resume, self._resume_state = self._resume_state, None
        skip = 0
        if resume:
            skip = int(resume.get("cursor") or 0)
            sampler_state = resume.get("sampler")
            if sampler_state and self.batch_sampler is not None and \
                    hasattr(self.batch_sampler, "load_state_dict"):
                self.batch_sampler.load_state_dict(sampler_state)
        self._quarantined = []
        it = _PrefetchIterator(self, skip_batches=skip)
        self._latest_iter = weakref.ref(it)
        return it

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------- resume
    def _active_iter(self) -> Optional[_PrefetchIterator]:
        return self._latest_iter() if self._latest_iter is not None else None

    @property
    def quarantined(self) -> list:
        """[(sample_index, reason), ...] quarantined by the most recent
        iteration (skip_bad_samples mode)."""
        return list(self._quarantined)

    def state_dict(self) -> dict:
        """Checkpointable position: ``cursor`` (index batches already
        consumed this epoch) + the sampler's epoch/RNG state as of this
        epoch's start. Mid-iteration this captures the ACTIVE iterator,
        so an emergency save at a step boundary loses nothing."""
        it = self._active_iter()
        if it is not None and not it._exhausted:
            return {"cursor": int(it._cursor),
                    "sampler": dict(it._sampler_state)}
        if self._resume_state is not None:  # loaded, not yet iterated
            return {"cursor": int(self._resume_state.get("cursor") or 0),
                    "sampler": dict(self._resume_state.get("sampler") or {})}
        bs = self.batch_sampler
        sampler = bs.state_dict() \
            if bs is not None and hasattr(bs, "state_dict") else {}
        return {"cursor": 0, "sampler": sampler}

    def load_state_dict(self, state: dict) -> int:
        """Arm the next ``__iter__`` to resume: restore the sampler
        state, then fast-forward ``cursor`` index batches. Leaves may be
        checkpoint-restored Tensors/0-d arrays — coerced here. Returns
        the cursor."""
        state = _coerce_state(dict(state or {}))
        cursor = int(state.get("cursor") or 0)
        self._resume_state = {"cursor": cursor,
                              "sampler": state.get("sampler") or {}}
        return cursor

    @property
    def resumed_mid_epoch(self) -> bool:
        """True while a loaded, not-yet-replayed resume state points
        into the middle of an epoch (cursor > 0)."""
        return bool(self._resume_state
                    and self._resume_state.get("cursor", 0) > 0)


class WorkerInfo:
    """Info about the current DataLoader worker (reference
    fluid/dataloader/worker.py WorkerInfo)."""

    def __init__(self, id: int, num_workers: int, seed: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_WORKER_INFO = None


def get_worker_info():
    """In a worker process: that worker's WorkerInfo; None in the main
    process (reference io.get_worker_info)."""
    return _WORKER_INFO
