"""Samplers incl. DistributedBatchSampler (≈ python/paddle/io/
BatchSampler, python/paddle/fluid/dataloader/batch_sampler.py:
DistributedBatchSampler — rank-sharded indices with padding).

Checkpointable (the t5x/Grain deterministic-input contract): every
sampler exposes ``state_dict()/load_state_dict()``, and the shuffling
samplers derive each epoch's permutation from a STORED (seed, epoch)
pair via ``np.random.SeedSequence`` — never from the global RNG — so a
resumed job replays the exact same index stream. The base seed is drawn
once at construction (from the global RNG, so ``paddle.seed`` still
makes whole runs reproducible) and checkpointed with the epoch.
"""
from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np


def _draw_base_seed(generator) -> int:
    """Resolve a sampler's stored base seed: an explicit int, a
    np.random.Generator to draw from, or None -> one draw from the
    global RNG (the only global-RNG touch; everything after is derived
    from the stored value).

    The None draw prefers the framework RNG (``paddle.seed``) so a
    seeded program gets a reproducible shuffle order across fresh
    processes; NumPy's global RNG (process entropy unless the user
    seeded it) is only the fallback when paddle.seed was never called."""
    if generator is None:
        from ..core import random as _random
        if _random.get_seed() is not None:
            import jax
            return int(jax.random.randint(
                _random.next_key(), (), 0, 2 ** 31 - 1))
        return int(np.random.randint(0, 2 ** 31 - 1))
    if isinstance(generator, (int, np.integer)):
        return int(generator)
    if isinstance(generator, np.random.Generator):
        return int(generator.integers(0, 2 ** 31 - 1))
    raise TypeError(
        f"generator must be None, an int seed, or np.random.Generator; "
        f"got {type(generator)}")


def _epoch_rng(seed: int, epoch: int) -> np.random.Generator:
    """The per-epoch generator: seed and epoch folded through a
    SeedSequence, so epochs are decorrelated and replayable."""
    return np.random.Generator(
        np.random.PCG64(np.random.SeedSequence([int(seed), int(epoch)])))


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    # stateless by default; stateful subclasses override both
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    """Shuffling sampler with a stored per-epoch seed schedule: each
    ``__iter__`` draws the CURRENT epoch's permutation then advances the
    epoch, so consecutive epochs shuffle differently while
    ``state_dict()`` -> ``load_state_dict()`` replays any epoch
    exactly."""

    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self._seed = _draw_base_seed(generator)
        self._epoch = 0

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)

    def __iter__(self):
        n = len(self.data_source)
        rng = _epoch_rng(self._seed, self._epoch)
        self._epoch += 1
        if self.replacement:
            idx = rng.integers(0, n, self.num_samples)
        else:
            idx = rng.permutation(n)[:self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples

    def state_dict(self) -> dict:
        return {"seed": int(self._seed), "epoch": int(self._epoch)}

    def load_state_dict(self, state: dict) -> None:
        self._seed = int(state.get("seed", self._seed))
        self._epoch = int(state.get("epoch", self._epoch))


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True,
                 generator=None):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement
        self._seed = _draw_base_seed(generator)
        self._epoch = 0

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = _epoch_rng(self._seed, self._epoch)
        self._epoch += 1
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples

    def state_dict(self) -> dict:
        return {"seed": int(self._seed), "epoch": int(self._epoch)}

    def load_state_dict(self, state: dict) -> None:
        self._seed = int(state.get("seed", self._seed))
        self._epoch = int(state.get("epoch", self._epoch))


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self) -> Iterator[List[int]]:
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    # position/RNG state lives in the wrapped sampler
    def state_dict(self) -> dict:
        return self.sampler.state_dict() \
            if hasattr(self.sampler, "state_dict") else {}

    def load_state_dict(self, state: dict) -> None:
        if hasattr(self.sampler, "load_state_dict"):
            self.sampler.load_state_dict(state)


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks with padding so
    every rank sees the same number of batches (required for lockstep SPMD
    execution — same reason the reference pads:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler).

    The shuffle permutation is seeded by the epoch alone (reference
    contract: ``set_epoch`` on every rank keeps the ranks' shards
    aligned), so ``state_dict()`` only needs the epoch."""

    def __init__(self, dataset, batch_size, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as dist_env
            num_replicas = num_replicas or dist_env.get_world_size()
            rank = rank if rank is not None else dist_env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to total_size then take this rank's strided shard
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def state_dict(self) -> dict:
        return {"epoch": int(self.epoch)}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state.get("epoch", self.epoch))
