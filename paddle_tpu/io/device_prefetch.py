"""Sharded double-buffered host->device prefetch.

The train step's input pipeline has three serialization hazards:
(1) a synchronous ``device_put`` at call time means the H2D transfer of
batch N starts only after step N-1's dispatch — it never overlaps
compute; (2) an UNCOMMITTED placement (no sharding) gets re-placed by
the consumer (``DistributedTrainStep._shard_batch``), paying the
transfer twice; (3) consumed input buffers linger in HBM until Python
GC notices.

``DevicePrefetcher`` fixes all three: it keeps batch N+1's transfer in
flight while the consumer computes on batch N (``jax.device_put``
returns immediately; the runtime streams the copy in the background),
places each leaf COMMITTED on its target ``NamedSharding`` (taken from
the train step via ``prefetch_to_device(loader, step)``) so downstream
placement is idempotent and skipped, and — opt-in ``donate=True`` —
deletes the previous batch's device buffers the moment the consumer
asks for the next one (the runtime defers the actual free until any
in-flight execution using them completes).

Reference analog: the buffered multi-device readers Paddle hides
behind ``fluid.io.DataLoader(..., use_double_buffer=True)`` and the
flax/jax_utils ``prefetch_to_device`` idiom.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, Optional

import jax
import numpy as np

from ..core import monitor
from ..core.tensor import Tensor


def _resolve(shardings, leaf):
    """Target sharding for one leaf: None (default device, uncommitted),
    a single Sharding for every leaf, or a callable leaf -> Sharding."""
    if shardings is None:
        return None
    if callable(shardings):
        return shardings(leaf)
    return shardings


def _on_target(arr, target) -> bool:
    """True when ``arr`` is a device array already resident on
    ``target`` — re-placing it would be a no-op transfer, so the caller
    skips (and counts) it instead."""
    if not isinstance(arr, jax.Array):
        return False
    sh = getattr(arr, "sharding", None)
    if sh is None:
        return False
    if target is None:
        return True  # any device residency satisfies "on device"
    try:
        return sh.is_equivalent_to(target, arr.ndim)
    except (AttributeError, TypeError, ValueError):
        return sh == target


def place_batch(batch, shardings=None, stats=None):
    """Launch the async device placement of every array leaf of a host
    batch; returns the batch with leaves as device-backed Tensors.

    Placement is IDEMPOTENT: a leaf already resident on its target
    sharding is passed through untouched (counted in
    ``io.host2device.skipped``); everything else is placed committed
    when a sharding is given (``io.host2device.placed`` / ``.bytes``).
    """
    local = stats if stats is not None else [0, 0, 0]

    def put(x):
        arr = x._data if isinstance(x, Tensor) else x
        if not isinstance(arr, (np.ndarray, jax.Array)):
            return x
        target = _resolve(shardings, arr)
        if _on_target(arr, target):
            local[1] += 1
            return x if isinstance(x, Tensor) else Tensor(arr)
        local[0] += 1
        local[2] += int(getattr(arr, "nbytes", 0))
        placed = jax.device_put(arr, target) if target is not None \
            else jax.device_put(arr)
        return Tensor(placed)

    out = jax.tree_util.tree_map(
        put, batch, is_leaf=lambda t: isinstance(t, Tensor))
    if stats is None and monitor.enabled:
        monitor.record_host2device(*local)
    return out


def _device_leaves(batch):
    for leaf in jax.tree_util.tree_leaves(
            batch, is_leaf=lambda t: isinstance(t, Tensor)):
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if isinstance(arr, jax.Array):
            yield arr


class DevicePrefetcher:
    """Iterate ``source`` with ``depth`` batches' H2D transfers in
    flight ahead of the consumer (depth=1 = classic double buffering).

    ``shardings``: per-leaf target (see :func:`place_batch`) — pass the
    train step's ``batch_sharding_for`` so leaves land pre-sharded.
    ``donate=True`` deletes the PREVIOUS batch's device buffers when
    the next one is requested: the consumer must not touch a yielded
    batch after asking for its successor (a training loop never does).
    Leaves shared with the next batch (repeated-batch microbenchmarks)
    are never deleted.
    """

    def __init__(self, source: Iterable, shardings=None,
                 donate: bool = False, depth: int = 1):
        self.source = source
        self.shardings = shardings
        self.donate = bool(donate)
        self.depth = max(1, int(depth))

    def __iter__(self):
        it = iter(self.source)
        buf: collections.deque = collections.deque()
        exhausted = False
        stats = [0, 0, 0]

        def pull():
            nonlocal exhausted
            if exhausted:
                return
            try:
                nxt = next(it)
            except StopIteration:
                exhausted = True
                return
            buf.append(place_batch(nxt, self.shardings, stats))
            if monitor.enabled and (stats[0] or stats[1]):
                monitor.record_host2device(*stats)
                stats[0] = stats[1] = stats[2] = 0

        for _ in range(self.depth + 1):
            pull()
        prev = None
        while buf:
            cur = buf.popleft()
            pull()  # N+1 transfers while the consumer computes N
            if self.donate and prev is not None:
                keep = {id(a) for a in _device_leaves(cur)}
                for arr in _device_leaves(prev):
                    if id(arr) in keep:
                        continue
                    try:
                        arr.delete()
                    except Exception:
                        pass  # already donated/deleted elsewhere
            prev = cur
            yield cur

    def __len__(self):
        return len(self.source)


def prefetch_to_device(source: Iterable, train_step=None, shardings=None,
                       donate: bool = False, depth: int = 1):
    """Wrap a batch iterable so device placement overlaps compute,
    sharded for ``train_step``'s inputs when one is given::

        step = fleet.DistributedTrainStep(model, opt, loss_fn)
        for x, y in prefetch_to_device(loader, step):
            loss = step(x, y)   # no re-placement: leaves arrive sharded
    """
    if shardings is None and train_step is not None:
        shardings = getattr(train_step, "batch_sharding_for", None)
    return DevicePrefetcher(source, shardings=shardings, donate=donate,
                            depth=depth)
