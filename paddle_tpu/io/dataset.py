"""Datasets (≈ python/paddle/io/ Dataset family,
python/paddle/fluid/dataloader/dataset.py)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(getattr(t, "data", t)) for t in tensors]
        n = arrays[0].shape[0]
        assert all(a.shape[0] == n for a in arrays)
        self.arrays = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return self.arrays[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = datasets
        n = len(datasets[0])
        assert all(len(d) == n for d in datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, acc = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[acc:acc + ln].tolist()))
        acc += ln
    return out


def no_download_gate(name: str):
    """Zero-egress environment: datasets cannot download; standard
    archives must be provided locally (shared by text/audio/vision
    dataset readers)."""
    raise RuntimeError(
        f"{name}: download is unavailable in this environment; place "
        f"the standard archive/files locally and pass the data path")
