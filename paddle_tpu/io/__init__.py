from .dataloader import (DataLoader, DataLoaderWorkerError,  # noqa: F401
                         WorkerInfo, get_worker_info)
from .device_prefetch import (DevicePrefetcher, place_batch,  # noqa: F401
                              prefetch_to_device)
from .token_loader import TokenLoader  # noqa: F401
from .dataset import (ChainDataset, ComposeDataset, Dataset,  # noqa: F401
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler,  # noqa: F401
                      RandomSampler, Sampler, SequenceSampler,
                      WeightedRandomSampler)
