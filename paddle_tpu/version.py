"""paddle.version analog (python/paddle/version.py is generated at
build time with commit/version info)."""
import subprocess as _sp

from . import __version__ as full_version  # single source of truth

major, minor, patch = full_version.split(".")[:3]
rc = "0"
cuda_version = "False"   # no CUDA anywhere in this stack
cudnn_version = "False"
xpu_version = "False"
tpu = True


def _commit() -> str:
    """Commit of the paddle_tpu checkout ITSELF — only trust git if the
    repo root actually contains this package (a wheel inside someone
    else's checkout must report 'unknown', not their HEAD)."""
    import os
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    try:
        top = _sp.run(["git", "rev-parse", "--show-toplevel"],
                      cwd=pkg_dir, capture_output=True, text=True,
                      timeout=5).stdout.strip()
        # the repo root must be EXACTLY the package's parent dir —
        # git finds some ancestor repo for any installed wheel too
        if not top or top != os.path.dirname(pkg_dir):
            return "unknown"
        out = _sp.run(["git", "rev-parse", "HEAD"], cwd=pkg_dir,
                      capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or "unknown"
    except (OSError, _sp.TimeoutExpired):
        return "unknown"


_commit_cache = None


def __getattr__(name):
    # commit resolved lazily: no subprocess on plain `paddle.version`
    global _commit_cache
    if name == "commit":
        if _commit_cache is None:
            _commit_cache = _commit()
        return _commit_cache
    raise AttributeError(name)


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {__getattr__('commit')}")
    print("tpu: True (jax/XLA backend)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
