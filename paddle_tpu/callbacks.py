"""paddle.callbacks namespace (reference python/paddle/callbacks.py
re-exports hapi.callbacks)."""
from .hapi.callbacks import Callback  # noqa: F401
from .hapi.callbacks import ProgBarLogger  # noqa: F401
from .hapi.callbacks import ModelCheckpoint  # noqa: F401
from .hapi.callbacks import VisualDL  # noqa: F401
from .hapi.callbacks import LRSchedulerCallback as LRScheduler  # noqa: F401
from .hapi.callbacks import EarlyStopping  # noqa: F401
from .hapi.callbacks import ReduceLROnPlateau  # noqa: F401
from .hapi.callbacks import TerminateOnNaN  # noqa: F401
from .hapi.callbacks import MetricsCallback  # noqa: F401

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "TerminateOnNaN", "MetricsCallback"]
