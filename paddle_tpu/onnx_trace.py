"""Trace-based ONNX export: jaxpr -> ONNX graph.

Reference analog: python/paddle/onnx/export.py:21 — paddle2onnx walks
the traced Program op-by-op. TPU-native: the model's forward is traced
to a jaxpr (the framework's real IR) and each primitive maps to ONNX
nodes, so ANY traceable composition exports — residual adds,
attention matmuls/softmax, reshapes/transposes, convs/pools — not just
Sequential chains (onnx_proto.export_onnx remains the legacy walker).
Weights arrive as jaxpr constants and become initializers.
dot_general maps to Einsum (opset 12) with a generated equation, which
covers every contraction the MXU sees without shape gymnastics.

The artifact is validated end-to-end by the in-repo numpy evaluator
(onnx_eval.run_onnx) against the framework forward —
tests/test_onnx_trace.py does this for ResNet-18 and an ERNIE encoder
block.
"""
from __future__ import annotations

import string
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from .onnx_proto import _node, _tensor, _value_info, encode_model

__all__ = ["trace_to_onnx"]


class _Frame:
    """Per-jaxpr-invocation variable environment. Inner jaxprs of jit/
    custom_vjp calls are SHARED objects (jax caches them), so their
    vars must be bound per call, never globally."""

    def __init__(self):
        self.env: Dict[Any, str] = {}         # var -> onnx name
        self.cenv: Dict[Any, np.ndarray] = {}  # var -> folded constant


class _Graph:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self.const_vals: Dict[str, np.ndarray] = {}  # initializer values
        self.counter = 0
        self.min_opset = 13
        self.dyn_batch: Optional[int] = None  # traced batch size when
        # the export declares the leading input dim symbolic
        self.batch_src: Optional[str] = None  # graph input whose dim 0
        # IS the runtime batch (Shape-of feeds dynamic Expand targets)
        self._batch_1d: Optional[str] = None

    def runtime_batch_1d(self):
        """[1]-shaped int64 tensor holding the RUNTIME batch size,
        emitted once: Shape(input)[0:1]."""
        if self._batch_1d is None:
            shp = self.emit("Shape", [self.batch_src])
            self._batch_1d = self.emit("Slice", [
                shp,
                self.init_const(np.asarray([0], np.int64)),
                self.init_const(np.asarray([1], np.int64)),
                self.init_const(np.asarray([0], np.int64)),
                self.init_const(np.asarray([1], np.int64))])
        return self._batch_1d

    def fresh(self, base="t"):
        self.counter += 1
        return f"{base}_{self.counter}"

    def init_const(self, arr, base="c"):
        name = self.fresh(base)
        arr = np.asarray(arr)
        self.inits.append(_tensor(name, arr))
        self.const_vals[name] = arr
        return name

    def shape_const(self, dims):
        return self.init_const(np.asarray(dims, np.int64), "shape")

    def reshape_to(self, x_name, sizes, in_shape):
        """Emit a Reshape, keeping the graph batch-agnostic when the
        target's leading dim is the (symbolic) traced batch: ONNX
        Reshape dim 0 copies the input's runtime dim."""
        sizes = list(sizes)
        if self.dyn_batch and sizes and in_shape \
                and sizes[0] == self.dyn_batch \
                and in_shape[0] == self.dyn_batch:
            sizes[0] = 0
        return self.emit("Reshape", [x_name, self.shape_const(sizes)])

    def emit(self, op, inputs, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op, inputs, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def name_of(self, atom, frame: _Frame):
        from jax.extend.core import Literal
        if isinstance(atom, Literal):
            return self.init_const(np.asarray(atom.val), "lit")
        if atom not in frame.env and atom in frame.cenv:
            frame.env[atom] = self.init_const(frame.cenv[atom], "fold")
        return frame.env[atom]

    def const_of(self, atom, frame: _Frame):
        """Known constant value of a jaxpr atom, or None."""
        from jax.extend.core import Literal
        if isinstance(atom, Literal):
            return np.asarray(atom.val)
        if atom in frame.cenv:
            return frame.cenv[atom]
        name = frame.env.get(atom)
        if name is not None and name in self.const_vals:
            return self.const_vals[name]
        return None


def _einsum_eq(dn, lhs_rank, rhs_rank):
    """Build an einsum equation for dot_general dimension numbers."""
    (lc, rc), (lb, rb) = dn
    letters = iter(string.ascii_lowercase)
    lhs = [None] * lhs_rank
    rhs = [None] * rhs_rank
    out = []
    for i, j in zip(lb, rb):
        ch = next(letters)
        lhs[i] = rhs[j] = ch
        out.append(ch)
    for i, j in zip(lc, rc):
        ch = next(letters)
        lhs[i] = rhs[j] = ch
    for i in range(lhs_rank):
        if lhs[i] is None:
            lhs[i] = next(letters)
            out.append(lhs[i])
    for j in range(rhs_rank):
        if rhs[j] is None:
            rhs[j] = next(letters)
            out.append(rhs[j])
    return f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"


def _conv_node(g, eqn, in_names):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs_spec = dn.lhs_spec   # e.g. (0, 3, 1, 2) means position of N,C,H,W
    rhs_spec = dn.rhs_spec
    out_spec = dn.out_spec
    x, w = in_names
    ndim = len(lhs_spec)
    spatial = ndim - 2
    # transpose input to NCHW order if needed
    nchw = (0, 1) + tuple(range(2, ndim))
    if tuple(lhs_spec) != nchw:
        # lhs_spec[i] = where dim i of logical (N,C,spatial...) lives
        perm = list(lhs_spec)
        x = g.emit("Transpose", [x], perm=perm)
    if tuple(rhs_spec) != nchw:
        w = g.emit("Transpose", [w], perm=list(rhs_spec))
    pads = [pp for pp, _ in p["padding"]] + [pp for _, pp in p["padding"]]
    if any(d != 1 for d in p.get("lhs_dilation", (1,) * spatial)):
        raise NotImplementedError("transposed conv export not supported")
    out = g.emit("Conv", [x, w],
                 strides=list(p["window_strides"]),
                 pads=pads,
                 dilations=list(p.get("rhs_dilation",
                                      (1,) * spatial)),
                 group=int(p.get("feature_group_count", 1)))
    if tuple(out_spec) != nchw:
        # out_spec[i] = where logical dim i lives in the actual output;
        # we produced logical NCHW, so scatter it back
        inv = [0] * ndim
        for logical, actual in enumerate(out_spec):
            inv[actual] = logical
        out = g.emit("Transpose", [out], perm=inv)
    return out


def _reduce_window_node(g, eqn, in_names):
    p = eqn.params
    ndim = len(p["window_dimensions"])
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pad = p["padding"]
    if wd[0] != 1 or wd[1] != 1:
        raise NotImplementedError(
            "reduce_window over batch/channel dims not exportable")
    kind = "MaxPool" if eqn.primitive.name == "reduce_window_max" \
        else "AveragePool"
    attrs = dict(kernel_shape=list(wd[2:]), strides=list(ws[2:]),
                 pads=[pp for pp, _ in pad[2:]] + [pp for _, pp
                                                   in pad[2:]])
    if kind == "AveragePool":
        # sum-window = mean * k only when the divisor is the full
        # window everywhere — pad cells must count (ONNX default
        # count_include_pad=0 divides by the non-pad count at borders)
        attrs["count_include_pad"] = 1
    out = g.emit(kind, [in_names[0]], **attrs)
    if eqn.primitive.name == "reduce_window_sum":
        k = float(np.prod(wd[2:]))
        out = g.emit("Mul", [out, g.init_const(np.float32(k))])
    return out


def _broadcast_node(g, eqn, in_names):
    p = eqn.params
    shape = list(p["shape"])
    bcd = p["broadcast_dimensions"]
    in_aval = eqn.invars[0].aval
    # reshape to align: put size (or 1) at each broadcast position
    mid = [1] * len(shape)
    for src, dst in enumerate(bcd):
        mid[dst] = in_aval.shape[src]
    x = in_names[0]
    if list(in_aval.shape) != mid:
        x = g.reshape_to(x, mid, in_aval.shape)
    if mid != shape:
        if g.dyn_batch and shape and shape[0] == g.dyn_batch:
            # target's leading dim is the batch: build the Expand
            # shape at RUNTIME from Shape(input), so non-broadcasting
            # consumers (Concat, Einsum) see the true batch too
            rest = g.shape_const(shape[1:]) if len(shape) > 1 else None
            parts = [g.runtime_batch_1d()]
            if rest is not None:
                parts.append(rest)
            tgt = parts[0] if len(parts) == 1 else \
                g.emit("Concat", parts, axis=0)
            x = g.emit("Expand", [x, tgt])
        else:
            x = g.emit("Expand", [x, g.shape_const(shape)])
    return x


def _reduce_node(g, op, eqn, in_names):
    axes = list(eqn.params["axes"])
    g.min_opset = max(g.min_opset, 13)
    if op == "ReduceSum":  # axes as input from opset 13
        return g.emit("ReduceSum",
                      [in_names[0], g.init_const(
                          np.asarray(axes, np.int64), "axes")],
                      keepdims=0)
    return g.emit(op, [in_names[0]], axes=axes, keepdims=0)


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "exp": "Exp",
    "log": "Log", "tanh": "Tanh", "neg": "Neg", "abs": "Abs",
    "sign": "Sign", "erf": "Erf", "sqrt": "Sqrt", "floor": "Floor",
    "ceil": "Ceil", "logistic": "Sigmoid",
}

_IDENTITY_PRIMS = {"stop_gradient", "copy", "device_put",
                   "optimization_barrier"}


def _onnx_dtype(dt) -> Optional[int]:
    """ONNX TensorProto.DataType for a jax dtype (fp types collapse to
    FLOAT in this fp32 exporter)."""
    s = str(dt)
    if "float" in s or s == "bfloat16":
        return 1                   # FLOAT
    if s == "int64":
        return 7
    if s == "int32":
        return 6
    if s == "bool":
        return 9
    return None

_SUBJAXPR_PRIMS = {"jit", "pjit", "closed_call", "remat", "checkpoint",
                   "custom_jvp_call", "custom_vjp_call",
                   "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"}


def _sub_jaxpr(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            j = eqn.params[key]
            return j
    raise NotImplementedError(
        f"{eqn.primitive.name}: no inner jaxpr found")


def _walk(g: _Graph, jaxpr, in_names: List[str],
          const_bind=None) -> List[str]:
    frame = _Frame()
    for var, name in zip(jaxpr.invars, in_names):
        frame.env[var] = name
    for var, name in (const_bind or []):
        frame.env[var] = name
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name

        # constant folding: scalar/index math over known constants
        # (e.g. the clipped indices jnp.take builds for unbind) is
        # evaluated here instead of emitted as graph nodes
        cvals = [g.const_of(v, frame) for v in eqn.invars]
        foldable = (all(c is not None for c in cvals)
                    and all(int(np.prod(ov.aval.shape or (1,))) <= 4096
                            for ov in eqn.outvars)
                    # a dynamic-batch export must not bake
                    # batch-leading constants (e.g. zeros_like(ids)
                    # token-type ids) into the graph
                    and not (g.dyn_batch and any(
                        ov.aval.shape
                        and ov.aval.shape[0] == g.dyn_batch
                        for ov in eqn.outvars)))
        if foldable:
            try:
                if prim in _SUBJAXPR_PRIMS:
                    from jax.core import jaxpr_as_fun
                    sub = _sub_jaxpr(eqn)
                    vals = jaxpr_as_fun(sub)(*cvals)
                else:
                    vals = eqn.primitive.bind(*cvals, **eqn.params)
                if not isinstance(vals, (list, tuple)):
                    vals = [vals]
                for var, val in zip(eqn.outvars, vals):
                    frame.cenv[var] = np.asarray(val)
                continue
            except Exception:
                pass  # fall through to graph emission

        ins = [g.name_of(v, frame) for v in eqn.invars]

        if prim in _SUBJAXPR_PRIMS:
            sub = _sub_jaxpr(eqn)
            if hasattr(sub, "jaxpr"):   # ClosedJaxpr
                inner, consts = sub.jaxpr, list(sub.consts)
            else:
                inner, consts = sub, []
            cbind = [(var, g.init_const(np.asarray(c), "w"))
                     for var, c in zip(inner.constvars, consts)]
            if len(ins) > len(inner.invars):
                # num_consts-style leading operands already bound
                ins = ins[len(ins) - len(inner.invars):]
            outs = _walk(g, inner, ins, const_bind=cbind)
            for var, nm2 in zip(eqn.outvars, outs):
                frame.env[var] = nm2
            continue

        if prim in _IDENTITY_PRIMS:
            out = g.emit("Identity", [ins[0]])
        elif prim == "convert_element_type":
            src_dt = eqn.invars[0].aval.dtype
            dst_dt = eqn.outvars[0].aval.dtype
            dst = _onnx_dtype(dst_dt)
            if dst is None or _onnx_dtype(src_dt) == dst:
                # same ONNX type (incl. bf16<->f32 in an fp32 export):
                # no-op
                out = g.emit("Identity", [ins[0]])
            else:
                out = g.emit("Cast", [ins[0]], to=dst)
        elif prim in _ELEMENTWISE:
            out = g.emit(_ELEMENTWISE[prim], ins)
        elif prim == "integer_pow":
            out = g.emit("Pow", [ins[0], g.init_const(
                np.float32(eqn.params["y"]))])
        elif prim == "square":
            out = g.emit("Mul", [ins[0], ins[0]])
        elif prim == "cbrt":
            out = g.emit("Pow", [ins[0], g.init_const(
                np.float32(1.0 / 3.0))])
        elif prim == "rsqrt":
            out = g.emit("Sqrt", ins)
            out = g.emit("Reciprocal", [out])
        elif prim == "dot_general":
            eq = _einsum_eq(eqn.params["dimension_numbers"],
                            len(eqn.invars[0].aval.shape),
                            len(eqn.invars[1].aval.shape))
            out = g.emit("Einsum", ins, equation=eq)
        elif prim == "conv_general_dilated":
            out = _conv_node(g, eqn, ins)
        elif prim in ("reduce_window_max", "reduce_window_sum"):
            out = _reduce_window_node(g, eqn, ins)
        elif prim == "reduce_sum":
            out = _reduce_node(g, "ReduceSum", eqn, ins)
        elif prim == "reduce_max":
            out = _reduce_node(g, "ReduceMax", eqn, ins)
        elif prim == "reduce_min":
            out = _reduce_node(g, "ReduceMin", eqn, ins)
        elif prim == "reshape":
            out = g.reshape_to(ins[0], eqn.params["new_sizes"],
                               eqn.invars[0].aval.shape)
        elif prim == "transpose":
            out = g.emit("Transpose", [ins[0]],
                         perm=list(eqn.params["permutation"]))
        elif prim == "broadcast_in_dim":
            out = _broadcast_node(g, eqn, ins)
        elif prim in ("squeeze", "expand_dims"):
            out = g.reshape_to(ins[0], eqn.outvars[0].aval.shape,
                               eqn.invars[0].aval.shape)
        elif prim == "concatenate":
            out = g.emit("Concat", ins,
                         axis=int(eqn.params["dimension"]))
        elif prim == "select_n":
            if len(ins) != 3:
                raise NotImplementedError("select_n with >2 cases")
            # select_n(pred, on_false, on_true); Where(c, X, Y)=X if c
            out = g.emit("Where", [ins[0], ins[2], ins[1]])
        elif prim == "pad":
            lo_hi = eqn.params["padding_config"]
            if any(i != 0 for _, _, i in lo_hi) or \
                    any(l < 0 or h < 0 for l, h, _ in lo_hi):
                raise NotImplementedError(
                    "interior/negative padding not exportable")
            pads = [l for l, _, _ in lo_hi] + [h for _, h, _ in lo_hi]
            out = g.emit("Pad", [ins[0],
                                 g.init_const(np.asarray(pads, np.int64),
                                              "pads"),
                                 ins[1]], mode="constant")
        elif prim == "slice":
            p = eqn.params
            nd = len(p["start_indices"])
            limits = list(p["limit_indices"])
            in_shape = eqn.invars[0].aval.shape
            if g.dyn_batch and limits and in_shape \
                    and p["start_indices"][0] == 0 \
                    and limits[0] == in_shape[0] == g.dyn_batch:
                # full-extent batch slice: ONNX clamps out-of-range
                # ends, so a huge end keeps the graph batch-agnostic
                limits[0] = 2 ** 62
            out = g.emit("Slice", [
                ins[0],
                g.init_const(np.asarray(p["start_indices"], np.int64)),
                g.init_const(np.asarray(limits, np.int64)),
                g.init_const(np.asarray(range(nd), np.int64)),
                g.init_const(np.asarray(p["strides"] or [1] * nd,
                                        np.int64))])
        elif prim == "gather":
            dn = eqn.params["dimension_numbers"]
            idx = g.const_vals.get(ins[1])
            op_shape = tuple(eqn.invars[0].aval.shape)
            idx_shape = tuple(eqn.invars[1].aval.shape)
            ss = tuple(eqn.params["slice_sizes"])
            if idx is not None and np.asarray(idx).size == 1 \
                    and len(dn.start_index_map) == 1:
                # static-index pattern (unbind/x[i]): Slice + Reshape
                d = dn.start_index_map[0]
                i0 = int(np.asarray(idx).ravel()[0])
                out = g.emit("Slice", [
                    ins[0],
                    g.init_const(np.asarray([i0], np.int64)),
                    g.init_const(np.asarray([i0 + 1], np.int64)),
                    g.init_const(np.asarray([d], np.int64)),
                    g.init_const(np.asarray([1], np.int64))])
                slice_shape = list(eqn.invars[0].aval.shape)
                slice_shape[d] = 1
                out = g.reshape_to(out, eqn.outvars[0].aval.shape,
                                   slice_shape)
            else:
                # dynamic axis-gather (jnp.take / embedding lookup):
                # indices [..., 1], one collapsed slice dim d, full
                # slice sizes elsewhere — exactly ONNX Gather(axis=d).
                # NB: jax's out-of-range fill semantics are NOT
                # preserved; the export assumes in-range indices (the
                # same contract paddle2onnx emits).
                d = dn.start_index_map[0] \
                    if len(dn.start_index_map) == 1 else -1
                K = len(idx_shape) - 1
                R = len(op_shape)
                expected_ss = op_shape[:d] + (1,) + op_shape[d + 1:] \
                    if d >= 0 else None
                expected_off = tuple(
                    list(range(0, d)) + list(range(d + K, R - 1 + K))) \
                    if d >= 0 else None
                if (d < 0 or idx_shape[-1:] != (1,)
                        or dn.collapsed_slice_dims != (d,)
                        or ss != expected_ss
                        or tuple(dn.offset_dims) != expected_off):
                    raise NotImplementedError(
                        "gather outside the axis-gather (jnp.take) "
                        "and static-index patterns is not "
                        "ONNX-exportable")
                flat_idx = g.reshape_to(ins[1], idx_shape[:-1],
                                        idx_shape)
                out = g.emit("Gather", [ins[0], flat_idx], axis=d)
        elif prim == "iota":
            aval = eqn.outvars[0].aval
            dim = eqn.params["dimension"]
            arr = np.broadcast_to(
                np.arange(aval.shape[dim]).reshape(
                    [-1 if i == dim else 1
                     for i in range(len(aval.shape))]),
                aval.shape).astype(np.float32 if "float" in
                                   str(aval.dtype) else np.int64)
            out = g.init_const(arr, "iota")
        elif prim in ("eq", "ne", "lt", "le", "gt", "ge"):
            onnx_op = {"eq": "Equal", "lt": "Less", "gt": "Greater",
                       "le": "LessOrEqual", "ge": "GreaterOrEqual",
                       "ne": None}[prim]
            if onnx_op is None:
                out = g.emit("Equal", ins)
                out = g.emit("Not", [out])
            else:
                out = g.emit(onnx_op, ins)
        elif prim == "and":
            out = g.emit("And", ins)
        elif prim == "or":
            out = g.emit("Or", ins)
        elif prim == "not":
            out = g.emit("Not", ins)
        else:
            if all(c is not None for c in cvals) and g.dyn_batch:
                raise NotImplementedError(
                    f"jaxpr primitive {prim!r} has no ONNX mapping, "
                    f"and dynamic_batch=True blocked constant-folding "
                    f"its batch-leading result (folding would bake "
                    f"the traced batch size); export with "
                    f"dynamic_batch=False or rewrite the model to "
                    f"compute this from the input")
            raise NotImplementedError(
                f"jaxpr primitive {prim!r} has no ONNX mapping yet "
                f"(eqn: {eqn})")
        outs = [out] if isinstance(out, str) else out
        for var, nm2 in zip(eqn.outvars, outs):
            frame.env[var] = nm2
    return [g.name_of(v, frame) for v in jaxpr.outvars]


def trace_to_onnx(fn, example_inputs: Sequence, path: str,
                  opset: int = 13, input_names: Optional[List[str]]
                  = None, dynamic_batch: bool = False) -> str:
    """Trace `fn(*example_inputs)` (a pure function or an eval-mode
    Layer) to a jaxpr and serialize it as ONNX at `{path}.onnx`.
    Weights/constants become initializers. Returns the file path.

    dynamic_batch=True declares batch-sized leading input dims as the
    symbolic 'N' (the reference's dynamic-batch export): Reshapes that
    preserve the batch emit ONNX dim 0 (copy-from-input), Expand
    targets with a batch-leading dim are built at runtime from
    Shape(input), full-extent batch Slices get clamped huge ends, and
    constant folding refuses to bake batch-shaped constants. Caveat:
    the traced batch size is identified by VALUE, so trace with a
    batch unlikely to collide with fixed model dims (e.g. not 3 for a
    3-channel NCHW input ... use 5 or 7)."""
    from .core.tensor import Tensor
    from .nn.layer import Layer

    if isinstance(fn, Layer):
        layer = fn
        was_training = layer.training
        layer.eval()

        def pure(*args):
            out = layer(*[Tensor(a) for a in args])
            return jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))
    else:
        layer = None

        def pure(*args):
            out = fn(*[Tensor(a) for a in args])
            return jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))

    raw_inputs = [a._data if isinstance(a, Tensor) else np.asarray(a)
                  for a in example_inputs]
    try:
        closed = jax.make_jaxpr(pure)(*raw_inputs)
    finally:
        if layer is not None and was_training:
            layer.train()

    g = _Graph()
    g.min_opset = max(g.min_opset, opset)
    const_names = [g.init_const(np.asarray(c), "w")
                   for c in closed.consts]
    in_names = input_names or [f"input_{i}" if i else "input"
                               for i in range(len(raw_inputs))]
    if dynamic_batch and raw_inputs and np.asarray(raw_inputs[0]).ndim:
        g.dyn_batch = int(np.asarray(raw_inputs[0]).shape[0])
        g.batch_src = in_names[0]
    out_names = _walk(g, closed.jaxpr, in_names,
                      const_bind=list(zip(closed.jaxpr.constvars,
                                          const_names)))

    def vi(name, arr):
        elem = _onnx_dtype(np.asarray(arr).dtype) or 1
        shape = list(np.asarray(arr).shape)
        # only dims that ARE the traced batch become symbolic; other
        # inputs keep their concrete (baked) shapes honestly
        if g.dyn_batch and shape and shape[0] == g.dyn_batch:
            shape[0] = None          # dim_param "N" in the writer
        return _value_info(name, shape, elem)

    model = encode_model(
        g.nodes, g.inits,
        inputs=[vi(n, a) for n, a in zip(in_names, raw_inputs)],
        outputs=[_value_info(n, None) for n in out_names],
        opset=g.min_opset)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
