"""String compatibility helpers (reference: python/paddle/compat.py:21,
117 — `to_text` / `to_bytes` convert str/bytes and nested containers
between encodings; kept for API parity with code ported from the
py2-era fluid surface).
"""
from __future__ import annotations

__all__ = ["to_text", "to_bytes"]


def _convert(obj, conv, inplace):
    if obj is None:
        return obj
    if isinstance(obj, (list, set)):
        if inplace:
            items = [_convert(o, conv, inplace) for o in obj]
            if isinstance(obj, list):
                obj[:] = items
                return obj
            obj.clear()
            obj.update(items)
            return obj
        return type(obj)(_convert(o, conv, False) for o in obj)
    if isinstance(obj, dict):
        items = {_convert(k, conv, False): _convert(v, conv, False)
                 for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(items)
            return obj
        return items
    if isinstance(obj, tuple):
        return tuple(_convert(o, conv, False) for o in obj)
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """Convert bytes (possibly inside list/set/dict/tuple containers)
    to str using `encoding`; str and other types pass through."""

    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else o

    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Convert str (possibly inside containers) to bytes using
    `encoding`; bytes and other types pass through."""

    def conv(o):
        return o.encode(encoding) if isinstance(o, str) else o

    return _convert(obj, conv, inplace)
