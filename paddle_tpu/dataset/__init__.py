"""Legacy `paddle.dataset` namespace (reference:
python/paddle/dataset/ — uci_housing/imdb/imikolov/... modules whose
`train()`/`test()` return *reader creators* consumed by the
`paddle.reader` decorators and `paddle.batch`).

Each submodule here wraps the modern Dataset class (paddle_tpu.text
.datasets) in the reader-creator protocol: `train()` returns a no-arg
callable yielding the dataset's sample tuples. Downloads follow the
same policy as the underlying datasets (standard archive layouts,
egress-gated with a clear error when absent).
"""
from __future__ import annotations

from types import ModuleType as _Module
import sys as _sys

__all__ = ["uci_housing", "imdb", "imikolov", "movielens", "conll05",
           "wmt14", "wmt16"]


def _reader_module(name, dataset_cls, modes=("train", "test"),
                   pass_mode=True):
    mod = _Module(f"{__name__}.{name}")
    mod.__doc__ = (f"Reader-creator wrappers over "
                   f"paddle_tpu.text.datasets.{dataset_cls.__name__}")

    def _make(mode):
        def creator(**kwargs):
            if pass_mode:
                kwargs.setdefault("mode", mode)

            def reader():
                ds = dataset_cls(**kwargs)
                for i in range(len(ds)):
                    yield ds[i]
            return reader
        creator.__name__ = mode
        creator.__doc__ = (f"Reader creator over the {mode} split of "
                           f"{dataset_cls.__name__}; pass the class's "
                           f"kwargs (data paths etc.) through.")
        return creator

    for mode in modes:
        setattr(mod, mode, _make(mode))
    _sys.modules[mod.__name__] = mod
    return mod


def __getattr__(name):
    from ..text import datasets as _d
    table = {
        "uci_housing": (_d.UCIHousing, ("train", "test"), True),
        "imdb": (_d.Imdb, ("train", "test"), True),
        "imikolov": (_d.Imikolov, ("train", "test"), True),
        "movielens": (_d.Movielens, ("train", "test"), True),
        # the reference ships the test split only; Conll05st takes no mode
        "conll05": (_d.Conll05st, ("test",), False),
        "wmt14": (_d.WMT14, ("train", "test"), True),
        "wmt16": (_d.WMT16, ("train", "test"), True),
    }
    if name in table:
        cls, modes, pass_mode = table[name]
        mod = _reader_module(name, cls, modes, pass_mode)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.dataset' has no "
                         f"attribute {name!r}")
