"""paddle.onnx analog (python/paddle/onnx/export.py wraps paddle2onnx).

Two artifacts:
- format="onnx" (r3): REAL ONNX protobuf, hand-encoded wire format
  (onnx_proto.py) covering the Linear/Conv/Norm/activation/pool layer
  subset — loadable by any ONNX runtime, verifiable with
  `protoc --decode_raw`. No onnx/paddle2onnx dependency.
- format="stablehlo" (default): serialized StableHLO (`jax.export`) —
  the TPU-native serving artifact consumed directly by XLA and the
  paddle_tpu.inference.Predictor, covering EVERY model the framework
  traces. Models outside the ONNX subset raise NotImplementedError
  from format="onnx" with a pointer here.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: Optional[int] = None, **configs):
    """Export `layer` as a serving artifact at `path` (StableHLO).

    Mirrors paddle.onnx.export(layer, path, input_spec); the result
    loads with paddle_tpu.jit.load / inference.Config(path).
    """
    if configs.pop("format", "stablehlo") == "onnx":
        if input_spec is None:
            raise ValueError("format='onnx' needs input_spec (example "
                             "tensors or shaped specs) to trace")
        # trace-based path (r4): jaxpr -> ONNX handles any traceable
        # model (residuals, attention, ...). The Sequential walker
        # (onnx_proto.export_onnx) stays for shape-only input_spec.
        example = []
        spec_shapes = []
        for s in input_spec:
            if hasattr(s, "data") or isinstance(s, np.ndarray):
                example.append(s)
                spec_shapes.append(list(np.shape(np.asarray(
                    s.data if hasattr(s, "data") else s))))
            else:
                shape = list(getattr(s, "shape", s))
                spec_shapes.append([None if d is None or d < 0 else d
                                    for d in shape])
                # dynamic (None/-1) dims trace at a concrete size
                dtype = getattr(s, "dtype", None) or np.float32
                example.append(np.zeros(
                    [1 if d is None or d < 0 else d for d in shape],
                    dtype))
        try:
            from .onnx_trace import trace_to_onnx
            return trace_to_onnx(layer, example, path,
                                 opset=opset_version or 13)
        except NotImplementedError:
            # Sequential walker fallback keeps dynamic dims dynamic
            from .onnx_proto import export_onnx
            return export_onnx(layer, path, spec_shapes[0],
                               opset=opset_version or 13)
    from .jit.save_load import save
    save(layer, path, input_spec=input_spec)
    return path + ".stablehlo"
