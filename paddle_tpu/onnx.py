"""paddle.onnx analog (python/paddle/onnx/export.py wraps paddle2onnx).

Two artifacts:
- format="onnx" (r3): REAL ONNX protobuf, hand-encoded wire format
  (onnx_proto.py) covering the Linear/Conv/Norm/activation/pool layer
  subset — loadable by any ONNX runtime, verifiable with
  `protoc --decode_raw`. No onnx/paddle2onnx dependency.
- format="stablehlo" (default): serialized StableHLO (`jax.export`) —
  the TPU-native serving artifact consumed directly by XLA and the
  paddle_tpu.inference.Predictor, covering EVERY model the framework
  traces. Models outside the ONNX subset raise NotImplementedError
  from format="onnx" with a pointer here.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: Optional[int] = None, **configs):
    """Export `layer` as a serving artifact at `path` (StableHLO).

    Mirrors paddle.onnx.export(layer, path, input_spec); the result
    loads with paddle_tpu.jit.load / inference.Config(path).
    """
    if configs.pop("format", "stablehlo") == "onnx":
        from .onnx_proto import export_onnx
        shape = None
        if input_spec:
            s = input_spec[0]
            shape = list(getattr(s, "shape", None) or np.shape(s))
        if shape is None:
            raise ValueError("format='onnx' needs input_spec with a "
                             "shape for the graph input")
        return export_onnx(layer, path, shape,
                           opset=opset_version or 13)
    from .jit.save_load import save
    save(layer, path, input_spec=input_spec)
    return path + ".stablehlo"
