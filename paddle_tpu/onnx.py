"""paddle.onnx analog (python/paddle/onnx/export.py wraps paddle2onnx).

TPU-native: the portable serving artifact is serialized StableHLO
(`jax.export`), not ONNX — XLA consumes it directly and it
round-trips through paddle_tpu.inference.Predictor. export() therefore
produces a `{path}.stablehlo` bundle with the same call signature as
the reference's paddle.onnx.export; true ONNX emission would need the
(unavailable offline) onnx/paddle2onnx packages and is stubbed with a
clear error.
"""
from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: Optional[int] = None, **configs):
    """Export `layer` as a serving artifact at `path` (StableHLO).

    Mirrors paddle.onnx.export(layer, path, input_spec); the result
    loads with paddle_tpu.jit.load / inference.Config(path).
    """
    if configs.pop("format", "stablehlo") == "onnx":
        raise RuntimeError(
            "true ONNX emission requires the onnx/paddle2onnx packages, "
            "which are unavailable in this environment; the default "
            "StableHLO artifact serves the same deployment role on TPU")
    from .jit.save_load import save
    save(layer, path, input_spec=input_spec)
    return path + ".stablehlo"
