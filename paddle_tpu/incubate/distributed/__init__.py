"""paddle.incubate.distributed analog — legacy import paths kept for
migrating users; the real implementations live in
paddle_tpu.distributed.parallel."""
from . import models  # noqa: F401

__all__ = ["models"]
