"""MoE-aware gradient clipping (reference:
python/paddle/incubate/distributed/models/moe/grad_clip.py:21).

The reference needs a special clip class because under its expert
parallelism each rank physically holds ONLY its experts' gradients, so
a naive per-rank global norm is wrong and the class re-aggregates the
expert contribution across the MoE group.

Under this framework's GSPMD expert parallelism that failure mode does
not exist: expert weights are ep-sharded views of one logical array,
and the plain ClipGradByGlobalNorm reduction compiles to the correct
global psum over the mesh. tests/test_moe.py::
test_moe_global_norm_clip_parity_witness PROVES it — one clipped step
on a dp2 x ep4 mesh produces bit-compatible parameters with the
single-device run. This class therefore aliases the plain clip; it
exists so reference code importing it keeps working unchanged.
"""
from __future__ import annotations

from paddle_tpu.optimizer.grad_clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    """Drop-in for the reference class. The `moe_group` / `is_expert_param`
    arguments the reference takes are accepted and ignored: GSPMD's
    global reduction already covers expert shards (see module doc)."""

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm)
        self._is_expert_param_func = is_expert_param_func
        self._moe_group = moe_group
        self._group_name = group_name


ClipGradForMoEByGlobalNorm = ClipGradForMOEByGlobalNorm  # ref alias
