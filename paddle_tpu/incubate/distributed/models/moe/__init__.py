"""paddle.incubate.distributed.models.moe analog (reference:
python/paddle/incubate/distributed/models/moe/). The modern MoE layer
lives in paddle_tpu.distributed.parallel.moe and is re-exported here
under the reference's import path.

NOTE — constructor signature differs from the reference. The reference
``MoELayer(d_model, experts: LayerList, gate: dict | Gate,
moe_group=..., mp_group=..., recompute_interval=...)`` wraps
user-built expert Layers; here ``MoELayer`` is :class:`MoEMLP`, which
OWNS its stacked expert weights and takes ``(d_model, d_hidden,
num_experts, gate: str, top_k=, capacity_factor=)`` — process groups
are implicit in the 'ep' mesh axis and recompute is a train-step
concern (``fleet.utils.RecomputeConfig``). Migrating call sites must
switch construction to the MoEMLP form; only the *forward* contract
(tokens in, combined expert outputs + ``l_aux`` set per call) is
drop-in.
"""
from paddle_tpu.distributed.parallel.moe import (  # noqa: F401
    MoEMLP as MoELayer)
from .grad_clip import (ClipGradForMOEByGlobalNorm,  # noqa: F401
                        ClipGradForMoEByGlobalNorm)

__all__ = ["MoELayer", "ClipGradForMOEByGlobalNorm",
           "ClipGradForMoEByGlobalNorm"]
