"""paddle.incubate.distributed.models.moe analog (reference:
python/paddle/incubate/distributed/models/moe/). The modern MoE layer
lives in paddle_tpu.distributed.parallel.moe and is re-exported here
under the reference's import path."""
from paddle_tpu.distributed.parallel.moe import (  # noqa: F401
    MoEMLP as MoELayer)
from .grad_clip import (ClipGradForMOEByGlobalNorm,  # noqa: F401
                        ClipGradForMoEByGlobalNorm)

__all__ = ["MoELayer", "ClipGradForMOEByGlobalNorm",
           "ClipGradForMoEByGlobalNorm"]
