"""N:M sparsity mask generation (≈ fluid/contrib/sparsity/utils.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["get_mask_1d", "check_mask_1d", "get_mask_2d_greedy",
           "check_mask_2d", "create_mask"]


def get_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the n largest-|.| entries of every m consecutive elements
    along the last axis."""
    arr = np.asarray(mat)
    shape = arr.shape
    flat = arr.reshape(-1, shape[-1])
    cols = shape[-1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = np.abs(flat).reshape(flat.shape[0], -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[..., :n], True, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols]
    return mask.reshape(shape)


def check_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    """True iff every m-length group along the last axis has at most n
    non-zeros."""
    arr = np.asarray(mat)
    flat = arr.reshape(-1, arr.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = (flat != 0).reshape(flat.shape[0], -1, m)
    return bool((groups.sum(-1) <= n).all())


def get_mask_2d_greedy(mat: np.ndarray, n: int = 2,
                       m: int = 4) -> np.ndarray:
    """Greedy 2-D variant: n:m along BOTH the row and column grouping
    (reference get_mask_2d_greedy). Applies the 1-D rule to rows of
    each m x m tile, then enforces the column constraint greedily."""
    arr = np.asarray(mat)
    if arr.ndim != 2:
        raise ValueError("get_mask_2d_greedy expects a 2-D matrix")
    rows, cols = arr.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(np.abs(arr), ((0, pr), (0, pc)))
    mask = np.zeros_like(padded, dtype=bool)
    for r0 in range(0, padded.shape[0], m):
        for c0 in range(0, padded.shape[1], m):
            tile = padded[r0:r0 + m, c0:c0 + m]
            tmask = np.zeros_like(tile, dtype=bool)
            # pick entries largest-first subject to n-per-row/col
            order = np.dstack(np.unravel_index(
                np.argsort(-tile, axis=None), tile.shape))[0]
            rcount = np.zeros(m, dtype=int)
            ccount = np.zeros(m, dtype=int)
            for r, c in order:
                if rcount[r] < n and ccount[c] < n:
                    tmask[r, c] = True
                    rcount[r] += 1
                    ccount[c] += 1
            mask[r0:r0 + m, c0:c0 + m] = tmask
    return mask[:rows, :cols]


def check_mask_2d(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    arr = np.asarray(mat)
    ok_rows = check_mask_1d(arr, n, m)
    ok_cols = check_mask_1d(arr.T, n, m)
    return ok_rows and ok_cols


def create_mask(mat: np.ndarray, func_name: str = "mask_1d", n: int = 2,
                m: int = 4) -> np.ndarray:
    if func_name in ("mask_1d", "get_mask_1d"):
        return get_mask_1d(mat, n, m)
    if func_name in ("mask_2d_greedy", "get_mask_2d_greedy"):
        return get_mask_2d_greedy(mat, n, m)
    raise ValueError(f"unknown mask function {func_name!r}")
