"""ASP — automatic structured (2:4) sparsity.

Reference: python/paddle/incubate/asp (fluid/contrib/sparsity/asp.py:
calculate_density, decorate, prune_model; utils.py mask generation
check_mask_1d/get_mask_1d). TPU note: the MXU has no N:M sparse mode,
so 2:4 here preserves Paddle's training/pruning WORKFLOW (masked
weights + mask maintenance after each optimizer step) with dense
execution — the masks ride along for deployment to hardware that can
exploit them.
"""
from .asp import (ASPHelper, calculate_density, decorate,  # noqa: F401
                  prune_model, reset_excluded_layers,
                  set_excluded_layers)
from .utils import (check_mask_1d, check_mask_2d,  # noqa: F401
                    create_mask, get_mask_1d, get_mask_2d_greedy)
