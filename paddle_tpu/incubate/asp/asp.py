"""ASP workflow: prune_model + decorate (≈ fluid/contrib/sparsity/
asp.py ASPHelper, prune_model:1, decorate:1)."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...nn.layers_common import Conv2D, Linear
from .utils import create_mask

__all__ = ["ASPHelper", "prune_model", "decorate",
           "set_excluded_layers", "reset_excluded_layers",
           "calculate_density"]


class ASPHelper:
    """Computes masks and maintains them after optimizer steps. The
    mask is stored ON the Parameter (`p._asp_mask`) so mask lifetime
    tracks the parameter — no global registry to go stale or leak."""

    _supported = (Linear, Conv2D)
    _excluded: set = set()

    @classmethod
    def is_supported_layer(cls, layer: Layer, name: str) -> bool:
        return isinstance(layer, cls._supported) and \
            name not in cls._excluded and \
            name.split(".")[-1] not in cls._excluded

    @classmethod
    def prune_model(cls, model: Layer, n: int = 2, m: int = 4,
                    mask_algo: str = "mask_1d") -> Dict[str, jnp.ndarray]:
        masks: Dict[str, jnp.ndarray] = {}
        for name, layer in model.named_sublayers(include_self=True):
            if not cls.is_supported_layer(layer, name):
                continue
            w = layer.weight
            arr = np.asarray(w._data)
            if arr.ndim < 2:
                continue
            # N:M groups must run along the REDUCTION dim (that's what
            # sparse matmul hardware contracts over): Linear weight is
            # [in, out] -> group along axis 0 (via transpose); conv
            # weight [out, in, kh, kw] flattens to [out, in*kh*kw] ->
            # group along the last axis directly
            if arr.ndim > 2:
                mat = arr.reshape(arr.shape[0], -1)
                mask2d = create_mask(mat, func_name=mask_algo, n=n, m=m)
                mask_np = mask2d.reshape(arr.shape)
            else:
                mask_np = create_mask(arr.T, func_name=mask_algo,
                                      n=n, m=m).T
            mask = jnp.asarray(mask_np, dtype=w._data.dtype)
            w._data = w._data * mask
            w._asp_mask = mask
            masks[name] = mask
        return masks

    @classmethod
    def apply_masks(cls, params: List[Tensor]) -> None:
        for p in params:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._data = p._data * mask


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d"):
    """Compute N:M masks for supported layers, zero the weights, and
    remember the masks for `decorate`d optimizers."""
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=mask_algo)


def decorate(optimizer):
    """Wrap optimizer.step to re-apply sparsity masks after each update
    (the reference wraps minimize/step the same way)."""
    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        ASPHelper.apply_masks(optimizer._parameter_list)
        return out

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer


def set_excluded_layers(layer_names, main_program=None):
    ASPHelper._excluded.update(layer_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded.clear()


def calculate_density(mat) -> float:
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    return float((arr != 0).sum() / arr.size)
