"""paddle.incubate.nn analog — fused transformer layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention, FusedFeedForward,
FusedMultiTransformer:997) backed by the hand-fused CUDA ops
(operators/fused/fused_attention_op.cu, fused_feedforward_op.cu,
fused_multi_transformer_op.cu). TPU-native: "fused" means the Pallas
flash-attention kernel plus XLA's fusion of the surrounding
elementwise/norm work — one Layer maps to the same single-kernel-ish
schedule the reference hand-writes.
"""
from .fused_transformer import (FusedFeedForward,  # noqa: F401
                                FusedMultiHeadAttention,
                                FusedMultiTransformer)
