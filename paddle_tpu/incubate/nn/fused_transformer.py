"""Fused transformer layers (reference fused_transformer.py surface)."""
from __future__ import annotations

from typing import Optional

from ...nn import functional as F
from ...nn.container import LayerList
from ...nn.layer import Layer
from ...nn.layers_common import Dropout, LayerNorm, Linear
from ...ops.manipulation import reshape, transpose

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedMultiTransformer"]


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with the QKV projection in
    ONE matmul and attention through
    F.scaled_dot_product_attention (Pallas flash kernel when eligible)
    — the schedule fused_attention_op.cu hand-fuses."""

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout_rate: float = 0.0,
                 attn_dropout_rate: float = 0.0,
                 normalize_before: bool = False,
                 need_weights: bool = False, epsilon: float = 1e-5):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                "embed_dim must be divisible by num_heads")
        if need_weights:
            raise NotImplementedError(
                "need_weights=True materializes the attention matrix "
                "and defeats the fused path")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        b, s = x.shape[0], x.shape[1]
        qkv = reshape(self.qkv_proj(x),
                      [b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        out = self.out_proj(reshape(out, [b, s, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    """LN + two matmuls + activation; XLA fuses the elementwise tail
    into the matmuls (fused_feedforward_op.cu analog)."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 normalize_before: bool = False, epsilon: float = 1e-5):
        super().__init__()
        self.fc1 = Linear(d_model, dim_feedforward)
        self.fc2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.normalize_before = normalize_before
        self.activation = getattr(F, activation)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        x = self.fc2(self.dropout(self.activation(self.fc1(x))))
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedMultiTransformer(Layer):
    """Stack of fused attention+FFN blocks
    (fused_multi_transformer_op analog; reference
    incubate/nn/layer/fused_transformer.py:997). Pre-LN like the
    reference's default inference configuration."""

    def __init__(self, embed_dim: int, num_heads: int,
                 dim_feedforward: int, num_layers: int = 1,
                 dropout_rate: float = 0.0, activation: str = "gelu",
                 normalize_before: bool = True, epsilon: float = 1e-5):
        super().__init__()
        self.attns = LayerList([
            FusedMultiHeadAttention(embed_dim, num_heads,
                                    dropout_rate=dropout_rate,
                                    attn_dropout_rate=dropout_rate,
                                    normalize_before=normalize_before,
                                    epsilon=epsilon)
            for _ in range(num_layers)])
        self.ffns = LayerList([
            FusedFeedForward(embed_dim, dim_feedforward,
                             dropout_rate=dropout_rate,
                             activation=activation,
                             normalize_before=normalize_before,
                             epsilon=epsilon)
            for _ in range(num_layers)])

    def forward(self, x, attn_mask=None):
        for attn, ffn in zip(self.attns, self.ffns):
            x = ffn(attn(x, attn_mask=attn_mask))
        return x
