"""Functional autodiff prims (≈ python/paddle/incubate/autograd/:
primapi.py forward_grad/grad:22,105, functional.py jvp/vjp/Jacobian/
Hessian). The reference built a nascent JAX-like jvp/transpose system
on static graph ops (primops.py/primrules.py); here the real jax
transforms are the engine and the API mirrors the reference surface
over the Tensor facade."""
from .functional import (Hessian, Jacobian, forward_grad,  # noqa: F401
                         grad, jvp, vjp)
