"""jvp/vjp/Jacobian/Hessian over the Tensor facade.

Reference: python/paddle/incubate/autograd/functional.py (jvp:1,
vjp:1, Jacobian, Hessian) and primapi.py (forward_grad:22, grad:105).
"""
from __future__ import annotations

from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["jvp", "vjp", "grad", "forward_grad", "Jacobian", "Hessian"]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(e) for e in x)
    return x


def _wrap(x):
    if isinstance(x, jax.Array):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(e) for e in x)
    return x


def _as_tuple(x) -> Tuple:
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def _raw_fn(func: Callable) -> Callable:
    """Lift a Tensor-facade function to raw-array in/out."""

    def raw(*arrays):
        outs = func(*[Tensor(a) for a in arrays])
        return _unwrap(outs)

    return raw


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v). `v` defaults to ones
    (reference functional.jvp semantics)."""
    xs_t = _as_tuple(xs)
    raw_xs = tuple(_unwrap(x) for x in xs_t)
    if v is None:
        raw_v = tuple(jnp.ones_like(x) for x in raw_xs)
    else:
        raw_v = tuple(_unwrap(x) for x in _as_tuple(v))
    out, tangent = jax.jvp(_raw_fn(func), raw_xs, raw_v)
    return _wrap(out), _wrap(tangent)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns (func(xs), v^T @ J). `v` defaults to ones."""
    xs_t = _as_tuple(xs)
    raw_xs = tuple(_unwrap(x) for x in xs_t)
    out, pullback = jax.vjp(_raw_fn(func), *raw_xs)
    if v is None:
        raw_v = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_t = _as_tuple(v)
        raw_v = _unwrap(v_t[0]) if len(v_t) == 1 and not \
            isinstance(out, tuple) else tuple(_unwrap(e) for e in v_t)
    grads = pullback(raw_v)
    grads = grads[0] if len(xs_t) == 1 else grads
    return _wrap(out), _wrap(grads)


def grad(func: Callable, xs, v=None):
    """primapi.grad analog: reverse-mode gradient of (a scalar or
    v-weighted) output wrt xs."""
    _, g = vjp(func, xs, v)
    return g


def forward_grad(func: Callable, xs, xs_dot=None):
    """primapi.forward_grad analog: forward-mode directional grad."""
    _, t = jvp(func, xs, xs_dot)
    return t


class Jacobian:
    """Lazy full Jacobian (reference functional.Jacobian — row/col
    indexable). Computed once via jacrev on first access.

    Multi-input: pass a tuple; func is called as func(*xs) and the
    per-input Jacobians are flattened and concatenated along the input
    axis, reference-style ([M, N_total]). Batched mode expects 2-D
    [B, N] inputs and returns the per-sample [B, out..., N] diagonal.
    """

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs
        self._batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        multi = isinstance(self._xs, (list, tuple))
        raw_xs = tuple(_unwrap(x) for x in _as_tuple(self._xs))
        raw_f = _raw_fn(self._func)
        if self._batched:
            if multi:
                raise NotImplementedError(
                    "batched Jacobian supports a single input")
            raw_x = raw_xs[0]
            if raw_x.ndim != 2:
                raise NotImplementedError(
                    "batched Jacobian expects [batch, features] input, "
                    f"got shape {raw_x.shape}")
            jac = jax.jacrev(raw_f)(raw_x)  # [B, out..., B, N]
            idx = jnp.arange(raw_x.shape[0])
            self._mat = jac[idx, ..., idx, :]  # per-sample diagonal
            return self._mat
        jacs = jax.jacrev(raw_f, argnums=tuple(range(len(raw_xs))))(
            *raw_xs)
        # reference matrix layout for bare AND tuple inputs alike:
        # flatten each [out..., in...] block to 2-D, concat input axes
        flat = []
        for j, x in zip(jacs, raw_xs):
            out_sz = int(jnp.size(j)) // max(int(jnp.size(x)), 1)
            flat.append(jnp.reshape(j, (out_sz, int(jnp.size(x)))))
        self._mat = jnp.concatenate(flat, axis=-1)
        return self._mat

    def __getitem__(self, key):
        return Tensor(self._compute()[key])

    @property
    def shape(self):
        return tuple(self._compute().shape)

    def numpy(self):
        import numpy as np
        return np.asarray(self._compute())


class Hessian:
    """Lazy Hessian of a scalar-output function (reference
    functional.Hessian). Batched mode expects [B, N] input, a
    per-sample scalar output, and returns the [B, N, N] per-sample
    blocks."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs
        self._batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        multi = isinstance(self._xs, (list, tuple))
        raw_xs = tuple(_unwrap(x) for x in _as_tuple(self._xs))

        if self._batched:
            if multi:
                raise NotImplementedError(
                    "batched Hessian supports a single input")
            raw_x = raw_xs[0]
            if raw_x.ndim != 2:
                raise NotImplementedError(
                    "batched Hessian expects [batch, features] input, "
                    f"got shape {raw_x.shape}")

            def scalar(x):
                out = _unwrap(self._func(Tensor(x)))
                if int(jnp.size(out)) != raw_x.shape[0]:
                    raise ValueError(
                        "batched Hessian needs one scalar per sample; "
                        f"func returned {jnp.shape(out)} for batch "
                        f"{raw_x.shape[0]}")
                return jnp.sum(out)  # cross-sample terms are zero

            full = jax.hessian(scalar)(raw_x)
            idx = jnp.arange(raw_x.shape[0])
            self._mat = full[idx, :, idx, :]  # [B, N, N] blocks
            return self._mat

        # non-batched: flatten-concat inputs -> reference [N, N] layout;
        # func must return ONE scalar
        sizes = [int(jnp.size(x)) for x in raw_xs]
        shapes = [jnp.shape(x) for x in raw_xs]
        offs = [0]
        for s in sizes:
            offs.append(offs[-1] + s)

        def scalar(z):
            parts = [jnp.reshape(z[offs[i]:offs[i + 1]], shapes[i])
                     for i in range(len(raw_xs))]
            out = _unwrap(self._func(*[Tensor(p) for p in parts]))
            if int(jnp.size(out)) != 1:
                raise ValueError(
                    "Hessian requires a scalar-output function; got "
                    f"output shape {jnp.shape(out)} (use is_batched "
                    "for per-sample scalars)")
            return jnp.reshape(out, ())

        z0 = jnp.concatenate([jnp.ravel(x) for x in raw_xs])
        self._mat = jax.hessian(scalar)(z0)
        return self._mat

    def __getitem__(self, key):
        return Tensor(self._compute()[key])

    @property
    def shape(self):
        return tuple(self._compute().shape)

    def numpy(self):
        import numpy as np
        return np.asarray(self._compute())
