"""paddle.incubate analog — experimental subsystems.

Reference: python/paddle/incubate/ (autograd functional prims, asp 2:4
sparsity, distributed models). Populated incrementally; see submodules.
"""
from . import asp  # noqa: F401

__all__ = ["asp"]
__all__.append("distributed")


def __getattr__(name):
    # paddle.incubate.distributed pulls the whole fleet/auto_parallel
    # stack — keep it lazy, mirroring the top-level _LAZY design
    if name == "distributed":
        import importlib
        mod = importlib.import_module(".distributed", __name__)
        globals()["distributed"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from . import autograd  # noqa: F401,E402

__all__.append("autograd")
from . import nn  # noqa: F401,E402

__all__.append("nn")
from . import optimizer  # noqa: F401

# top-level incubate surface (reference python/paddle/incubate/__init__.py)
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..geometric import (segment_max, segment_mean,  # noqa: F401
                         segment_min, segment_sum)
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss without changing it (reference
    incubate identity_loss; IPU-era marker — reductions apply)."""
    import jax.numpy as jnp
    from ..core.tensor import dispatch
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    return dispatch(
        "identity_loss",
        lambda a: (jnp.sum(a) if red == "sum"
                   else jnp.mean(a) if red == "mean" else a),
        (x,), {})


def softmax_mask_fuse(x, mask):
    """Fused masked softmax (reference incubate softmax_mask_fuse CUDA
    kernel): on TPU XLA fuses the add+softmax — one dispatched op."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import dispatch
    return dispatch(
        "softmax_mask_fuse",
        lambda a, m: jax.nn.softmax(a + m, axis=-1), (x, mask), {})


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference fused upper-triangle variant)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import dispatch

    def impl(a):
        s = a.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, a.shape, a.ndim - 2)
        cols = jax.lax.broadcasted_iota(jnp.int32, a.shape, a.ndim - 1)
        masked = jnp.where(rows >= cols, a, jnp.asarray(-1e9, a.dtype))
        return jax.nn.softmax(masked, axis=-1)

    return dispatch("softmax_mask_fuse_upper_triangle", impl, (x,), {})


def _graph_gate(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"{name} produces data-dependent-shaped neighbor sets "
            "(dynamic sampling) — host-side graph sampling; use "
            "paddle.geometric segment/send_u_recv ops for on-device "
            "message passing and sample neighbors in the DataLoader")

    fn.__name__ = name
    return fn


graph_khop_sampler = _graph_gate("graph_khop_sampler")
graph_reindex = _graph_gate("graph_reindex")
graph_sample_neighbors = _graph_gate("graph_sample_neighbors")
