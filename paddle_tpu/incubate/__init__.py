"""paddle.incubate analog — experimental subsystems.

Reference: python/paddle/incubate/ (autograd functional prims, asp 2:4
sparsity, distributed models). Populated incrementally; see submodules.
"""
from . import asp  # noqa: F401

__all__ = ["asp"]
from . import autograd  # noqa: F401,E402

__all__.append("autograd")
from . import nn  # noqa: F401,E402

__all__.append("nn")
from . import optimizer  # noqa: F401
