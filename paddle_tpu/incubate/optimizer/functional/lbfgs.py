"""minimize_lbfgs: limited-memory BFGS (two-loop recursion) with
strong-Wolfe line search.

Reference analog: python/paddle/incubate/optimizer/functional/lbfgs.py
(minimize_lbfgs, Nocedal & Wright Alg 7.4/7.5 with a circular history).
TPU-native: fixed-shape [m, n] history buffers updated in a single
lax.while_loop; the two-loop recursion runs as lax.fori_loop passes so
the whole call jits to one XLA program.
"""
from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from .bfgs import _unwrap_fn
from .line_search import strong_wolfe

__all__ = ["minimize_lbfgs"]


class _State(NamedTuple):
    k: jnp.ndarray
    done: jnp.ndarray
    converged: jnp.ndarray
    x: jnp.ndarray
    f: jnp.ndarray
    g: jnp.ndarray
    S: jnp.ndarray        # [m, n] s-history (circular)
    Y: jnp.ndarray        # [m, n] y-history
    rho: jnp.ndarray      # [m]
    count: jnp.ndarray    # total updates stored
    gamma: jnp.ndarray    # H0 scaling sy/yy
    nfev: jnp.ndarray


def _two_loop(g, S, Y, rho, count, gamma, m):
    """Nocedal Alg 7.4 on circular buffers: oldest-to-newest order is
    positions [count-valid .. count-1] mod m."""
    valid = jnp.minimum(count, m)

    def bwd(i, carry):
        q, alphas = carry
        # newest first: j = count-1-i
        j = (count - 1 - i) % m
        use = i < valid
        a = jnp.where(use, rho[j] * (S[j] @ q), 0.0)
        q = q - jnp.where(use, a, 0.0) * Y[j]
        return q, alphas.at[i].set(a)

    q, alphas = jax.lax.fori_loop(
        0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
    r = gamma * q

    def fwd(i, r):
        # oldest first: reverse of the backward order
        ii = m - 1 - i
        j = (count - 1 - ii) % m
        use = ii < valid
        b = jnp.where(use, rho[j] * (Y[j] @ r), 0.0)
        return r + jnp.where(use, alphas[ii] - b, 0.0) * S[j]

    return jax.lax.fori_loop(0, m, fwd, r)


def minimize_lbfgs(objective_func: Callable, initial_position,
                   history_size: int = 100, max_iters: int = 50,
                   tolerance_grad: float = 1e-7,
                   tolerance_change: float = 1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn: str = "strong_wolfe",
                   max_line_search_iters: int = 50,
                   initial_step_length: float = 1.0,
                   dtype: str = "float32", name=None):
    """Minimize `objective_func` (1-D Tensor -> scalar) from
    `initial_position` keeping `history_size` curvature pairs. Returns
    (is_converge, num_func_calls, position, objective_value,
    objective_gradient) — the reference's signature."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"only line_search_fn='strong_wolfe' is supported, got "
            f"{line_search_fn!r}")
    if initial_inverse_hessian_estimate is not None:
        raise NotImplementedError(
            "minimize_lbfgs scales H0 from the latest curvature pair; "
            "an explicit initial_inverse_hessian_estimate is a "
            "full-matrix (BFGS) concept — use minimize_bfgs")
    raw = _unwrap_fn(objective_func)
    x0 = initial_position._data if isinstance(initial_position, Tensor) \
        else jnp.asarray(initial_position)
    x0 = x0.astype(dtype)
    n = x0.shape[0]
    m = int(history_size)
    vg = jax.value_and_grad(raw)
    f0, g0 = vg(x0)

    def body(s: _State) -> _State:
        p = -_two_loop(s.g, s.S, s.Y, s.rho, s.count, s.gamma, m)
        dphi0 = s.g @ p

        def phi(a):
            fv, gv = vg(s.x + a * p)
            return fv, gv @ p

        alpha, _, _, ls_nfev, ls_ok = strong_wolfe(
            phi, s.f, dphi0, alpha0=initial_step_length,
            max_iters=max_line_search_iters)
        x1 = s.x + alpha * p
        f1, g1 = vg(x1)
        sk = x1 - s.x
        yk = g1 - s.g
        sy = sk @ yk
        store = sy > 1e-10
        slot = s.count % m
        S1 = jnp.where(store, s.S.at[slot].set(sk), s.S)
        Y1 = jnp.where(store, s.Y.at[slot].set(yk), s.Y)
        rho1 = jnp.where(
            store, s.rho.at[slot].set(1.0 / jnp.where(sy == 0, 1.0, sy)),
            s.rho)
        count1 = jnp.where(store, s.count + 1, s.count)
        gamma1 = jnp.where(store, sy / (yk @ yk), s.gamma)
        gnorm = jnp.max(jnp.abs(g1))
        xchange = jnp.max(jnp.abs(sk))
        # a failed line search (alpha=0) makes xchange=0 — that is a
        # breakdown, not convergence
        ls_failed = (~ls_ok) & (alpha == 0)
        converged = (gnorm <= tolerance_grad) | \
                    ((xchange <= tolerance_change) & ~ls_failed)
        return _State(k=s.k + 1, done=converged | ls_failed,
                      converged=converged,
                      x=x1, f=f1, g=g1, S=S1, Y=Y1, rho=rho1,
                      count=count1, gamma=gamma1,
                      nfev=s.nfev + ls_nfev + 1)

    def cond(s: _State):
        return (~s.done) & (s.k < max_iters)

    init = _State(
        k=jnp.zeros((), jnp.int32),
        done=jnp.max(jnp.abs(g0)) <= tolerance_grad,
        converged=jnp.max(jnp.abs(g0)) <= tolerance_grad,
        x=x0, f=f0, g=g0,
        S=jnp.zeros((m, n), x0.dtype), Y=jnp.zeros((m, n), x0.dtype),
        rho=jnp.zeros((m,), x0.dtype),
        count=jnp.zeros((), jnp.int32),
        gamma=jnp.ones((), x0.dtype),
        nfev=jnp.ones((), jnp.int32))
    out = jax.lax.while_loop(cond, body, init)
    return (Tensor(out.converged), Tensor(out.nfev), Tensor(out.x),
            Tensor(out.f), Tensor(out.g))
