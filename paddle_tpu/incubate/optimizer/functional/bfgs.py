"""minimize_bfgs: full-matrix BFGS with strong-Wolfe line search.

Reference analog: python/paddle/incubate/optimizer/functional/bfgs.py
(minimize_bfgs, Nocedal & Wright Alg 6.1). TPU-native: the whole
optimization is one lax.while_loop — inverse-Hessian update, line
search and convergence checks are all traced ops, so the call jits to
a single XLA program.
"""
from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from .line_search import strong_wolfe

__all__ = ["minimize_bfgs"]


class _State(NamedTuple):
    k: jnp.ndarray
    done: jnp.ndarray
    converged: jnp.ndarray
    x: jnp.ndarray
    f: jnp.ndarray
    g: jnp.ndarray
    H: jnp.ndarray
    nfev: jnp.ndarray


def _unwrap_fn(objective_func):
    def f(x):
        out = objective_func(Tensor(x) if not isinstance(x, Tensor)
                             else x)
        return out._data if isinstance(out, Tensor) else out
    return f


def minimize_bfgs(objective_func: Callable, initial_position,
                  max_iters: int = 50, tolerance_grad: float = 1e-7,
                  tolerance_change: float = 1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn: str = "strong_wolfe",
                  max_line_search_iters: int = 50,
                  initial_step_length: float = 1.0,
                  dtype: str = "float32", name=None):
    """Minimize `objective_func` (1-D Tensor -> scalar) from
    `initial_position`. Returns (is_converge, num_func_calls, position,
    objective_value, objective_gradient) — the reference's signature."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"only line_search_fn='strong_wolfe' is supported, got "
            f"{line_search_fn!r}")
    raw = _unwrap_fn(objective_func)
    x0 = initial_position._data if isinstance(initial_position, Tensor) \
        else jnp.asarray(initial_position)
    x0 = x0.astype(dtype)
    n = x0.shape[0]
    I = jnp.eye(n, dtype=x0.dtype)
    H0 = I if initial_inverse_hessian_estimate is None else (
        initial_inverse_hessian_estimate._data
        if isinstance(initial_inverse_hessian_estimate, Tensor)
        else jnp.asarray(initial_inverse_hessian_estimate)).astype(dtype)
    vg = jax.value_and_grad(raw)
    f0, g0 = vg(x0)

    def body(s: _State) -> _State:
        p = -(s.H @ s.g)
        dphi0 = s.g @ p

        def phi(a):
            fv, gv = vg(s.x + a * p)
            return fv, gv @ p

        alpha, _, _, ls_nfev, ls_ok = strong_wolfe(
            phi, s.f, dphi0, alpha0=initial_step_length,
            max_iters=max_line_search_iters)
        x1 = s.x + alpha * p
        f1, g1 = vg(x1)
        sk = x1 - s.x
        yk = g1 - s.g
        sy = sk @ yk
        # curvature guard: skip the update when sy is not positive
        # (numerical breakdown); H stays s.H
        rho = jnp.where(sy > 1e-10, 1.0 / jnp.where(sy == 0, 1.0, sy),
                        0.0)
        V = I - rho * jnp.outer(sk, yk)
        H1 = jnp.where(sy > 1e-10,
                       V @ s.H @ V.T + rho * jnp.outer(sk, sk), s.H)
        gnorm = jnp.max(jnp.abs(g1))
        xchange = jnp.max(jnp.abs(sk))
        # a failed line search (alpha=0) makes xchange=0 — that is a
        # breakdown, not convergence
        ls_failed = (~ls_ok) & (alpha == 0)
        converged = (gnorm <= tolerance_grad) | \
                    ((xchange <= tolerance_change) & ~ls_failed)
        return _State(k=s.k + 1, done=converged | ls_failed,
                      converged=converged,
                      x=x1, f=f1, g=g1, H=H1,
                      nfev=s.nfev + ls_nfev + 1)

    def cond(s: _State):
        return (~s.done) & (s.k < max_iters)

    init = _State(k=jnp.zeros((), jnp.int32),
                  done=jnp.max(jnp.abs(g0)) <= tolerance_grad,
                  converged=jnp.max(jnp.abs(g0)) <= tolerance_grad,
                  x=x0, f=f0, g=g0, H=H0,
                  nfev=jnp.ones((), jnp.int32))
    out = jax.lax.while_loop(cond, body, init)
    return (Tensor(out.converged), Tensor(out.nfev), Tensor(out.x),
            Tensor(out.f), Tensor(out.g))
