"""paddle.incubate.optimizer.functional analogs: quasi-Newton
minimizers (reference python/paddle/incubate/optimizer/functional/
{bfgs,lbfgs,line_search}.py) as single-program lax.while_loop
optimizers."""
from .bfgs import minimize_bfgs
from .lbfgs import minimize_lbfgs

__all__ = ["minimize_bfgs", "minimize_lbfgs"]
