"""Strong-Wolfe line search as a single lax.while_loop state machine.

Reference analog: python/paddle/incubate/optimizer/functional/
line_search.py (strong_wolfe built from static-graph while ops).
TPU-native: one jittable while_loop whose state carries the
bracket/zoom phase flag, so the whole minimize_* call compiles to one
XLA program. Algorithm: Nocedal & Wright, Numerical Optimization 2e,
Algorithms 3.5 (bracketing) + 3.6 (zoom, bisection variant).
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class _LSState(NamedTuple):
    i: jnp.ndarray          # iteration counter
    stage: jnp.ndarray      # 0 = bracketing, 1 = zoom
    done: jnp.ndarray
    failed: jnp.ndarray
    nfev: jnp.ndarray
    a_prev: jnp.ndarray
    phi_prev: jnp.ndarray
    a_cur: jnp.ndarray
    a_lo: jnp.ndarray
    phi_lo: jnp.ndarray
    a_hi: jnp.ndarray
    phi_hi: jnp.ndarray
    a_star: jnp.ndarray
    phi_star: jnp.ndarray
    dphi_star: jnp.ndarray


def strong_wolfe(phi_fn: Callable, f0, dphi0, *, c1=1e-4, c2=0.9,
                 alpha0=1.0, max_iters=50
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray, jnp.ndarray]:
    """Find alpha satisfying the strong Wolfe conditions for the 1-D
    slice phi(alpha): phi_fn(alpha) -> (value, dvalue/dalpha).

    Returns (alpha, phi(alpha), dphi(alpha), n_evals, ok)."""
    dt = f0.dtype
    c1 = jnp.asarray(c1, dt)
    c2 = jnp.asarray(c2, dt)

    def armijo_fail(a, phi):
        return phi > f0 + c1 * a * dphi0

    def curvature_ok(dphi):
        return jnp.abs(dphi) <= -c2 * dphi0

    def body(s: _LSState) -> _LSState:
        a = jnp.where(s.stage == 0, s.a_cur, 0.5 * (s.a_lo + s.a_hi))
        phi, dphi = phi_fn(a)
        nfev = s.nfev + 1

        # ---------------- bracketing phase (Alg 3.5)
        to_zoom_hi = armijo_fail(a, phi) | ((phi >= s.phi_prev)
                                           & (s.i > 0))
        br_done = (~to_zoom_hi) & curvature_ok(dphi)
        to_zoom_rev = (~to_zoom_hi) & (~br_done) & (dphi >= 0)
        # continue bracketing with a doubled step otherwise
        b_stage = jnp.where(to_zoom_hi | to_zoom_rev, 1, 0)
        b_alo = jnp.where(to_zoom_hi, s.a_prev,
                          jnp.where(to_zoom_rev, a, s.a_prev))
        b_plo = jnp.where(to_zoom_hi, s.phi_prev,
                          jnp.where(to_zoom_rev, phi, s.phi_prev))
        b_ahi = jnp.where(to_zoom_hi, a,
                          jnp.where(to_zoom_rev, s.a_prev, s.a_hi))
        b_phi = jnp.where(to_zoom_hi, phi,
                          jnp.where(to_zoom_rev, s.phi_prev, s.phi_hi))

        # ---------------- zoom phase (Alg 3.6, bisection)
        z_hi_shrink = armijo_fail(a, phi) | (phi >= s.phi_lo)
        z_done = (~z_hi_shrink) & curvature_ok(dphi)
        z_flip = (~z_hi_shrink) & (~z_done) \
            & (dphi * (s.a_hi - s.a_lo) >= 0)
        z_alo = jnp.where(z_hi_shrink, s.a_lo, a)
        z_plo = jnp.where(z_hi_shrink, s.phi_lo, phi)
        z_ahi = jnp.where(z_hi_shrink, a,
                          jnp.where(z_flip, s.a_lo, s.a_hi))
        z_phi = jnp.where(z_hi_shrink, phi,
                          jnp.where(z_flip, s.phi_lo, s.phi_hi))
        # zoom interval collapsed without meeting curvature: accept lo
        z_fail = (~z_done) & (jnp.abs(s.a_hi - s.a_lo)
                              < jnp.asarray(1e-8, dt))

        in_zoom = s.stage == 1
        done = jnp.where(in_zoom, z_done | z_fail, br_done)
        stage = jnp.where(in_zoom, 1, b_stage)
        a_lo = jnp.where(in_zoom, z_alo, b_alo)
        phi_lo = jnp.where(in_zoom, z_plo, b_plo)
        a_hi = jnp.where(in_zoom, z_ahi, b_ahi)
        phi_hi = jnp.where(in_zoom, z_phi, b_phi)
        a_star = jnp.where(done, jnp.where(in_zoom & z_fail, s.a_lo, a),
                           s.a_star)
        phi_star = jnp.where(done,
                             jnp.where(in_zoom & z_fail, s.phi_lo, phi),
                             s.phi_star)
        dphi_star = jnp.where(done, dphi, s.dphi_star)
        return _LSState(
            i=s.i + 1, stage=stage, done=s.done | done,
            failed=s.failed | (in_zoom & z_fail),
            nfev=nfev, a_prev=a, phi_prev=phi,
            a_cur=jnp.where(stage == 0, 2.0 * a, s.a_cur),
            a_lo=a_lo, phi_lo=phi_lo, a_hi=a_hi, phi_hi=phi_hi,
            a_star=a_star, phi_star=phi_star, dphi_star=dphi_star)

    def cond(s: _LSState):
        return (~s.done) & (s.i < max_iters)

    z = jnp.zeros((), dt)
    init = _LSState(
        i=jnp.zeros((), jnp.int32), stage=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool), failed=jnp.zeros((), bool),
        nfev=jnp.zeros((), jnp.int32),
        a_prev=z, phi_prev=f0, a_cur=jnp.asarray(alpha0, dt),
        a_lo=z, phi_lo=f0, a_hi=z, phi_hi=f0,
        a_star=z, phi_star=f0, dphi_star=dphi0)
    out = jax.lax.while_loop(cond, body, init)
    # never satisfied within the budget: fall back to the best bracket
    a = jnp.where(out.done, out.a_star, out.a_lo)
    phi = jnp.where(out.done, out.phi_star, out.phi_lo)
    dphi = out.dphi_star
    ok = out.done & ~out.failed
    return a, phi, dphi, out.nfev, ok
