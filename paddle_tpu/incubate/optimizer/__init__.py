"""paddle.incubate.optimizer analogs.

Reference: python/paddle/incubate/optimizer/{lookahead,modelaverage}.py
(+ distributed_fused_lamb.py — on TPU the plain Lamb already compiles to
one fused XLA program under TrainStep, so no separate fused variant is
needed; optimizer/optimizers.py Lamb is the analog).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor
from ...optimizer.optimizer import Optimizer, opt_key

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """Lookahead wrapper (reference lookahead.py): every k inner steps,
    slow weights move alpha of the way toward the fast weights and the
    fast weights reset to the slow ones."""

    def __init__(self, inner_optimizer: Optimizer, alpha: float = 0.5,
                 k: int = 5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be within [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._k_count = 0
        self._parameter_list = inner_optimizer._parameter_list
        # slow weights snapshot the INITIAL params (reference
        # lookahead.py keeps slow_params from construction), so the
        # first k-step sync genuinely pulls back toward the start point
        self._slow: Dict[int, jnp.ndarray] = {
            opt_key(p): p.data for p in (self._parameter_list or [])
            if isinstance(p, Parameter)}

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k != 0:
            return
        for p in (self._parameter_list or []):
            if not isinstance(p, Parameter) or not p.trainable:
                continue
            key = opt_key(p)
            slow = self._slow.get(key)
            if slow is None:  # param added after construction
                slow = p.data
            slow = slow + self.alpha * (p.data - slow)
            self._slow[key] = slow
            p._replace_data(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        sd = {"inner": self.inner_optimizer.state_dict(),
              "_k_count": self._k_count}
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                s = self._slow.get(opt_key(p))
                if s is not None:
                    sd[f"slow_{i}"] = np.asarray(s)
        return sd

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd.get("inner", {}))
        self._k_count = int(sd.get("_k_count", 0))
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                if f"slow_{i}" in sd:
                    self._slow[opt_key(p)] = jnp.asarray(sd[f"slow_{i}"])


class ModelAverage(Optimizer):
    """Running average of parameter values for evaluation (reference
    modelaverage.py): accumulate sums each step; apply() swaps averaged
    weights in, restore() swaps the live ones back."""

    def __init__(self, average_window_rate: float = 0.15,
                 parameters: Optional[List] = None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        super().__init__(parameters=parameters)
        self.avg_rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum: Dict[int, jnp.ndarray] = {}
        self._cnt: Dict[int, int] = {}
        self._old_sum: Dict[int, jnp.ndarray] = {}
        self._old_cnt: Dict[int, int] = {}
        self._backup: Dict[int, jnp.ndarray] = {}
        self._applied = False

    def step(self):
        # two-buffer rolling window (the reference's sum/old_sum +
        # num_accumulates rotation): the live sum rotates into old_sum
        # when it reaches max_average_window, so apply() averages over
        # the most recent [max_w, 2*max_w) steps
        for p in (self._parameter_list or []):
            if not isinstance(p, Parameter) or not p.trainable:
                continue
            key = opt_key(p)
            cur = self._sum.get(key)
            self._sum[key] = p.data if cur is None else cur + p.data
            self._cnt[key] = self._cnt.get(key, 0) + 1
            if self._cnt[key] >= self.max_w:
                self._old_sum[key] = self._sum.pop(key)
                self._old_cnt[key] = self._cnt.pop(key)

    def apply(self, executor=None, need_restore: bool = True):
        """Swap averaged params in (context-manager friendly)."""
        for p in (self._parameter_list or []):
            key = opt_key(p)
            total = None
            n = 0
            if key in self._old_sum:
                total = self._old_sum[key]
                n += self._old_cnt[key]
            if key in self._sum:
                total = self._sum[key] if total is None \
                    else total + self._sum[key]
                n += self._cnt[key]
            if total is not None and n >= max(1, min(self.min_w,
                                                     self.max_w)):
                # reference gate: too few accumulates -> keep live
                # weights rather than swap in a high-variance average
                self._backup[key] = p.data
                p._replace_data(total / n)
        self._applied = True

        class _Ctx:
            def __enter__(s):
                return s

            def __exit__(s, *exc):
                if need_restore:
                    self.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        for p in (self._parameter_list or []):
            key = opt_key(p)
            if key in self._backup:
                p._replace_data(self._backup.pop(key))
        self._applied = False


from . import functional  # noqa: F401,E402  (minimize_bfgs/minimize_lbfgs)

__all__.append("functional")
