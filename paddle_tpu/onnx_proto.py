"""Minimal ONNX emitter — hand-encoded protobuf wire format.

The environment has no `onnx`/`paddle2onnx` packages, but ONNX files
are plain protobuf: this module serializes a valid ModelProto (field
numbers from the public onnx.proto schema, opset 13) for the
Linear/Conv/Norm layer subset (VERDICT r2 Next #9). The output loads
in any ONNX runtime; `decode_raw`-style parsing (tests, or
`protoc --decode_raw`) shows the expected structure.

Supported layer types (walked from Sequential composition, eval mode):
Linear -> Gemm, Conv2D -> Conv, BatchNorm{1,2}D -> BatchNormalization,
LayerNorm -> LayerNormalization (opset 17), ReLU -> Relu,
Sigmoid -> Sigmoid, Tanh -> Tanh, GELU -> Gelu, Softmax -> Softmax,
MaxPool2D -> MaxPool, AvgPool2D -> AveragePool,
AdaptiveAvgPool2D(1) -> GlobalAveragePool, Flatten -> Flatten,
Dropout(eval) -> Identity.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["encode_model", "export_onnx", "parse_wire"]


# ------------------------------------------------------------ wire writer

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_int(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _f_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def _f_str(field: int, value: str) -> bytes:
    return _f_bytes(field, value.encode())


def _f_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


# onnx.AttributeProto.AttributeType
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR = 1, 2, 3, 4
_AT_FLOATS, _AT_INTS = 6, 7
# onnx.TensorProto.DataType
DT_FLOAT, DT_INT64, DT_INT32, DT_BOOL = 1, 7, 6, 9


def _tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    if arr.dtype == np.int64:
        dtype = DT_INT64
    elif arr.dtype == np.int32:
        dtype = DT_INT32
    elif arr.dtype == np.bool_:
        dtype = DT_BOOL
    else:
        arr = arr.astype(np.float32)
        dtype = DT_FLOAT
    body = b"".join(_f_int(1, d) for d in arr.shape)
    body += _f_int(2, dtype)
    body += _f_str(8, name)
    body += _f_bytes(9, arr.tobytes())          # raw_data
    return body


def _attr(name: str, value) -> bytes:
    body = _f_str(1, name)
    if isinstance(value, bool):
        body += _f_int(3, int(value)) + _f_int(20, _AT_INT)
    elif isinstance(value, int):
        body += _f_int(3, value) + _f_int(20, _AT_INT)
    elif isinstance(value, float):
        body += _f_float(2, value) + _f_int(20, _AT_FLOAT)
    elif isinstance(value, str):
        body += _f_bytes(4, value.encode()) + _f_int(20, _AT_STRING)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], float):
        body += b"".join(_tag(7, 5) + struct.pack("<f", v)
                         for v in value)
        body += _f_int(20, _AT_FLOATS)
    elif isinstance(value, (list, tuple)):
        body += b"".join(_f_int(8, int(v)) for v in value)
        body += _f_int(20, _AT_INTS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return body


def _node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
          name: str = "", **attrs) -> bytes:
    body = b"".join(_f_str(1, i) for i in inputs)
    body += b"".join(_f_str(2, o) for o in outputs)
    body += _f_str(3, name or f"{op_type}_{outputs[0]}")
    body += _f_str(4, op_type)
    body += b"".join(_f_bytes(5, _attr(k, v))
                     for k, v in attrs.items())
    return body


def _value_info(name: str, shape: Optional[Sequence[Optional[int]]],
                elem_type: int = DT_FLOAT) -> bytes:
    """shape=None -> unknown rank (no TensorShapeProto at all), the
    correct declaration for outputs whose rank the walker does not
    track; a wrong declared rank fails onnx shape inference."""
    tensor_type = _f_int(1, elem_type)
    if shape is not None:
        dims = b""
        for d in shape:
            dim = _f_int(1, int(d)) if d is not None and d >= 0 \
                else _f_str(2, "N")
            dims += _f_bytes(1, dim)
        tensor_type += _f_bytes(2, dims)
    type_proto = _f_bytes(1, tensor_type)
    return _f_str(1, name) + _f_bytes(2, type_proto)


def encode_model(nodes: List[bytes], initializers: List[bytes],
                 inputs: List[bytes], outputs: List[bytes],
                 opset: int = 13, producer: str = "paddle_tpu",
                 graph_name: str = "graph") -> bytes:
    graph = b"".join(_f_bytes(1, n) for n in nodes)
    graph += _f_str(2, graph_name)
    graph += b"".join(_f_bytes(5, t) for t in initializers)
    graph += b"".join(_f_bytes(11, i) for i in inputs)
    graph += b"".join(_f_bytes(12, o) for o in outputs)
    opset_b = _f_str(1, "") + _f_int(2, opset)
    model = _f_int(1, 8)                 # ir_version 8
    model += _f_str(2, producer)
    model += _f_bytes(7, graph)
    model += _f_bytes(8, opset_b)
    return model


# ------------------------------------------------------------ layer walk

def _walk_layers(layer) -> List[Tuple[str, Any]]:
    """Flatten supported compositions into an ordered op list."""
    from .nn.container import Sequential
    if isinstance(layer, Sequential):
        out = []
        for name, sub in layer.named_children():
            out.extend(_walk_layers(sub))
        return out
    return [(type(layer).__name__, layer)]


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def export_onnx(layer, path: str, input_shape: Sequence[Optional[int]],
                opset: int = 13) -> str:
    """Serialize `layer` (a Sequential of supported layer types, eval
    mode) to `{path}.onnx`. Returns the file path; raises
    NotImplementedError for layers outside the subset (callers fall
    back to the StableHLO artifact)."""
    from .nn import layers_common as L

    ops = _walk_layers(layer)
    nodes: List[bytes] = []
    inits: List[bytes] = []
    min_opset = [opset]
    cur = "input"
    counter = [0]

    def nm(base):
        counter[0] += 1
        return f"{base}_{counter[0]}"

    def add_init(name, arr):
        inits.append(_tensor(name, np.asarray(arr)))
        return name

    for kind, sub in ops:
        out = nm("t")
        if kind == "Linear":
            w = add_init(nm("W"), np.asarray(sub.weight.numpy()))
            names = [cur, w]
            if sub.bias is not None:
                names.append(add_init(nm("B"),
                                      np.asarray(sub.bias.numpy())))
            nodes.append(_node("Gemm", names, [out], alpha=1.0,
                               beta=1.0, transB=0))
        elif kind == "Conv2D":
            if getattr(sub, "data_format", "NCHW") != "NCHW":
                raise NotImplementedError(
                    "ONNX Conv expects NCHW; export the NCHW variant")
            w = add_init(nm("W"), np.asarray(sub.weight.numpy()))
            names = [cur, w]
            if sub.bias is not None:
                names.append(add_init(nm("B"),
                                      np.asarray(sub.bias.numpy())))
            pads = _pair(sub.padding)
            nodes.append(_node(
                "Conv", names, [out],
                kernel_shape=list(np.asarray(sub.weight.shape)[2:]),
                strides=_pair(sub.stride),
                dilations=_pair(sub.dilation),
                group=int(getattr(sub, "groups", 1)),
                pads=pads + pads))
        elif kind in ("BatchNorm1D", "BatchNorm2D", "BatchNorm"):
            c = sub._mean.shape[0]
            ones = np.ones(c, np.float32)
            zeros = np.zeros(c, np.float32)
            g = add_init(nm("gamma"), sub.weight.numpy()
                         if sub.weight is not None else ones)
            b = add_init(nm("beta"), sub.bias.numpy()
                         if sub.bias is not None else zeros)
            m = add_init(nm("mean"), sub._mean.numpy())
            v = add_init(nm("var"), sub._variance.numpy())
            nodes.append(_node("BatchNormalization",
                               [cur, g, b, m, v], [out],
                               epsilon=float(sub.epsilon)))
        elif kind == "LayerNorm":
            min_opset[0] = max(min_opset[0], 17)  # LN lands in op17
            g = add_init(nm("gamma"), sub.weight.numpy())
            b = add_init(nm("beta"), sub.bias.numpy())
            nodes.append(_node("LayerNormalization", [cur, g, b],
                               [out], epsilon=float(sub._epsilon
                                                    if hasattr(sub, "_epsilon")
                                                    else sub.epsilon),
                               axis=-1))
        elif kind == "ReLU":
            nodes.append(_node("Relu", [cur], [out]))
        elif kind == "Sigmoid":
            nodes.append(_node("Sigmoid", [cur], [out]))
        elif kind == "Tanh":
            nodes.append(_node("Tanh", [cur], [out]))
        elif kind == "GELU":
            # ONNX defines Gelu only from opset 20
            min_opset[0] = max(min_opset[0], 20)
            nodes.append(_node("Gelu", [cur], [out]))
        elif kind == "Softmax":
            nodes.append(_node("Softmax", [cur], [out],
                               axis=int(getattr(sub, "axis", -1))))
        elif kind == "MaxPool2D":
            nodes.append(_node(
                "MaxPool", [cur], [out],
                kernel_shape=_pair(sub.kernel_size),
                strides=_pair(sub.stride or sub.kernel_size),
                pads=_pair(sub.padding) + _pair(sub.padding)))
        elif kind == "AvgPool2D":
            nodes.append(_node(
                "AveragePool", [cur], [out],
                kernel_shape=_pair(sub.kernel_size),
                strides=_pair(sub.stride or sub.kernel_size),
                pads=_pair(sub.padding) + _pair(sub.padding)))
        elif kind == "AdaptiveAvgPool2D":
            osz = sub.output_size
            if osz not in (1, (1, 1), [1, 1]):
                raise NotImplementedError(
                    "only global AdaptiveAvgPool2D(1) maps to ONNX")
            nodes.append(_node("GlobalAveragePool", [cur], [out]))
        elif kind == "Flatten":
            stop = int(getattr(sub, "stop_axis", -1))
            if stop != -1:
                raise NotImplementedError(
                    "ONNX Flatten folds ALL trailing dims; "
                    f"stop_axis={stop} has no ONNX equivalent — use "
                    "the StableHLO artifact")
            nodes.append(_node("Flatten", [cur], [out],
                               axis=int(getattr(sub, "start_axis", 1))))
        elif kind == "Dropout":
            nodes.append(_node("Identity", [cur], [out]))
        else:
            raise NotImplementedError(
                f"layer type {kind} is outside the ONNX-exportable "
                f"subset (Linear/Conv/Norm/activations/pools); use the "
                f"StableHLO artifact for full-coverage serving")
        cur = out

    model = encode_model(
        nodes, inits,
        inputs=[_value_info("input", input_shape)],
        outputs=[_value_info(cur, None)],
        opset=min_opset[0])
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path


# ------------------------------------------------------------ wire reader

def parse_wire(data: bytes) -> List[Tuple[int, int, Any]]:
    """Decode one protobuf message level into (field, wire_type, value)
    triples — the `protoc --decode_raw` analog used by tests."""
    out = []
    i = 0

    def rd_varint():
        nonlocal i
        shift = n = 0
        while True:
            b = data[i]
            i += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    while i < len(data):
        key = rd_varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            out.append((field, wire, rd_varint()))
        elif wire == 2:
            ln = rd_varint()
            out.append((field, wire, data[i:i + ln]))
            i += ln
        elif wire == 5:
            out.append((field, wire,
                        struct.unpack("<f", data[i:i + 4])[0]))
            i += 4
        elif wire == 1:
            out.append((field, wire,
                        struct.unpack("<d", data[i:i + 8])[0]))
            i += 8
        else:
            raise ValueError(f"wire type {wire}")
    return out
