"""MobileNet v1/v2/v3 (≈ python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py). Depthwise convs are grouped convs —
XLA lowers them to efficient TPU convolutions."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Dropout, Hardsigmoid, Hardswish, Linear,
                                ReLU, ReLU6)
from ..ops.manipulation import flatten


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(Layer):
    def __init__(self, c_in, c_out, k, stride=1, groups=1, act=ReLU):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(c_out)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


# ------------------------------------------------------------------- v1
class DepthwiseSeparable(Layer):
    def __init__(self, c_in, c_mid, c_out, stride):
        super().__init__()
        self.dw = ConvBNLayer(c_in, c_mid, 3, stride=stride, groups=c_in)
        self.pw = ConvBNLayer(c_mid, c_out, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.blocks = Sequential(*[
            DepthwiseSeparable(s(ci), s(ci), s(co), st)
            for ci, co, st in cfg])
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


# ------------------------------------------------------------------- v2
class InvertedResidual(Layer):
    def __init__(self, c_in, c_out, stride, expand_ratio):
        super().__init__()
        hidden = int(round(c_in * expand_ratio))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(c_in, hidden, 1, act=ReLU6))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=ReLU6),
            ConvBNLayer(hidden, c_out, 1, act=None)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
               (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
               (6, 320, 1, 1)]
        c_in = _make_divisible(32 * scale)
        features = [ConvBNLayer(3, c_in, 3, stride=2, act=ReLU6)]
        for t, c, n, s in cfg:
            c_out = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    c_in, c_out, s if i == 0 else 1, t))
                c_in = c_out
        self.last_c = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNLayer(c_in, self.last_c, 1, act=ReLU6))
        self.features = Sequential(*features)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(self.last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


# ------------------------------------------------------------------- v3
class SqueezeExcite(Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        mid = _make_divisible(channels // reduction)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(channels, mid, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(mid, channels, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class V3Block(Layer):
    def __init__(self, c_in, c_mid, c_out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        self.expand = ConvBNLayer(c_in, c_mid, 1, act=act) \
            if c_mid != c_in else None
        self.dw = ConvBNLayer(c_mid, c_mid, k, stride=stride,
                              groups=c_mid, act=act)
        self.se = SqueezeExcite(c_mid) if use_se else None
        self.pw = ConvBNLayer(c_mid, c_out, 1, act=None)

    def forward(self, x):
        out = x if self.expand is None else self.expand(x)
        out = self.dw(out)
        if self.se is not None:
            out = self.se(out)
        out = self.pw(out)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, ReLU, 1), (3, 64, 24, False, ReLU, 2),
    (3, 72, 24, False, ReLU, 1), (5, 72, 40, True, ReLU, 2),
    (5, 120, 40, True, ReLU, 1), (5, 120, 40, True, ReLU, 1),
    (3, 240, 80, False, Hardswish, 2), (3, 200, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1), (3, 184, 80, False, Hardswish, 1),
    (3, 480, 112, True, Hardswish, 1), (3, 672, 112, True, Hardswish, 1),
    (5, 672, 160, True, Hardswish, 2), (5, 960, 160, True, Hardswish, 1),
    (5, 960, 160, True, Hardswish, 1)]
_V3_SMALL = [
    (3, 16, 16, True, ReLU, 2), (3, 72, 24, False, ReLU, 2),
    (3, 88, 24, False, ReLU, 1), (5, 96, 40, True, Hardswish, 2),
    (5, 240, 40, True, Hardswish, 1), (5, 240, 40, True, Hardswish, 1),
    (5, 120, 48, True, Hardswish, 1), (5, 144, 48, True, Hardswish, 1),
    (5, 288, 96, True, Hardswish, 2), (5, 576, 96, True, Hardswish, 1),
    (5, 576, 96, True, Hardswish, 1)]


class MobileNetV3(Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        c_in = _make_divisible(16 * scale)
        layers = [ConvBNLayer(3, c_in, 3, stride=2, act=Hardswish)]
        for k, exp, c, se, act, s in cfg:
            c_mid = _make_divisible(exp * scale)
            c_out = _make_divisible(c * scale)
            layers.append(V3Block(c_in, c_mid, c_out, k, s, se, act))
            c_in = c_out
        c_last = _make_divisible(cfg[-1][1] * scale)
        layers.append(ConvBNLayer(c_in, c_last, 1, act=Hardswish))
        self.features = Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(c_last, last_c), Hardswish(), Dropout(0.2),
                Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


def mobilenet_v3_large(scale=1.0, **kw):
    return MobileNetV3(_V3_LARGE, 1280, scale=scale, **kw)


def mobilenet_v3_small(scale=1.0, **kw):
    return MobileNetV3(_V3_SMALL, 1024, scale=scale, **kw)
