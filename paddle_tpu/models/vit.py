"""Vision Transformer (BASELINE.md config #5: ViT-L as the
layout-sensitive vision flagship; capability analog of the reference's
`python/paddle/vision/models/` model-zoo surface, which predates ViT —
built here on the same TPU-first kit as models/gpt.py).

Patch embedding is a strided Conv2D (one big MXU matmul after XLA's
im2col), encoder blocks are pre-LN with mp-sharded attention/FFN."""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.tensor import Parameter
from ..distributed.parallel.mp_layers import sharded_constraint
from ..distributed.parallel.recompute import recompute
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Conv2D, Dropout, LayerNorm, Linear


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    in_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-6
    initializer_range: float = 0.02
    use_recompute: bool = False

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


from ._common import spec_linear as _linear


class ViTAttention(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        std = cfg.initializer_range
        self.qkv_proj = _linear(h, 3 * h, std, P(None, "mp"), P("mp"))
        self.out_proj = _linear(h, h, std / math.sqrt(2 * cfg.num_layers),
                                P("mp", None), P())
        self.dropout_p = cfg.dropout

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        qkv = sharded_constraint(qkv, P(("dp", "sharding"), None, "mp"))
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=False, dropout_p=self.dropout_p,
            training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class ViTBlock(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        std = cfg.initializer_range
        ffn = int(cfg.hidden_size * cfg.mlp_ratio)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = ViTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.fc1 = _linear(cfg.hidden_size, ffn, std, P(None, "mp"), P("mp"))
        self.fc2 = _linear(ffn, cfg.hidden_size,
                           std / math.sqrt(2 * cfg.num_layers),
                           P("mp", None), P())
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        h = F.gelu(self.fc1(self.ln2(x)), approximate=True)
        return x + self.dropout(self.fc2(h))


class ViT(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        self.cfg = cfg
        self.patch_embed = Conv2D(cfg.in_channels, cfg.hidden_size,
                                  cfg.patch_size, stride=cfg.patch_size)
        self.cls_token = Parameter(
            np.zeros([1, 1, cfg.hidden_size], dtype=np.float32))
        self.pos_embed = Parameter(I.TruncatedNormal(
            0.0, cfg.initializer_range)(
            [1, cfg.num_patches + 1, cfg.hidden_size]))
        self.pos_drop = Dropout(cfg.dropout)
        self.blocks = LayerList([ViTBlock(cfg)
                                 for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size,
                              epsilon=cfg.layer_norm_epsilon)
        self.head = _linear(cfg.hidden_size, cfg.num_classes,
                            cfg.initializer_range, P(), P()) \
            if cfg.num_classes > 0 else None

    def forward(self, x):
        """x: [b, c, H, W] NCHW (paddle.vision convention)."""
        from .. import ops
        x = self.patch_embed(x)                       # [b, h, H/p, W/p]
        b, h = x.shape[0], x.shape[1]
        x = x.reshape([b, h, -1]).transpose([0, 2, 1])  # [b, n, h]
        cls = ops.manipulation.broadcast_to(
            self.cls_token, [b, 1, h])
        x = ops.manipulation.concat([cls, x], axis=1) + self.pos_embed
        x = sharded_constraint(x, P(("dp", "sharding"), None, None))
        x = self.pos_drop(x)
        for block in self.blocks:
            if self.cfg.use_recompute and self.training:
                x = recompute(block, x, policy="save_dots")
            else:
                x = block(x)
        x = self.ln_f(x)
        return self.head(x[:, 0]) if self.head is not None else x[:, 0]


CONFIGS = {
    "vit-b-16": ViTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "vit-l-16": ViTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "vit-h-14": ViTConfig(patch_size=14, hidden_size=1280, num_layers=32,
                          num_heads=16),
    "test-tiny": ViTConfig(image_size=32, patch_size=8, hidden_size=64,
                           num_layers=2, num_heads=4, num_classes=10),
}


def vit(name: str = "vit-b-16", **overrides) -> ViT:
    import dataclasses
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ViT(cfg)
