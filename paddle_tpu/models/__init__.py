# Submodules keep their names (models.gpt / models.ernie / models.vit are
# modules); the ernie()/vit() factories stay on their submodules to avoid
# shadowing them here.
from . import ernie, gpt, resnet, vit  # noqa: F401
from .ernie import (ErnieConfig, ErnieForPretraining,  # noqa: F401
                    ErnieModel)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .lenet import LeNet  # noqa: F401
from .resnet import (resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152)
from .vit import ViT, ViTConfig  # noqa: F401
