from . import gpt, resnet  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .lenet import LeNet  # noqa: F401
from .resnet import (resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152)
