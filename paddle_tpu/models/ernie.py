"""ERNIE / BERT bidirectional transformer encoder — the BASELINE.md
config #3 pretraining flagship (capability analog of the reference's
ERNIE models trained with Fleet; the reference repo itself only carries
the GPT fixture `python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py`,
so this mirrors the public ERNIE-3.0 / BERT architecture on the same
TPU-first layer kit as models/gpt.py).

TPU-first design: weights carry PartitionSpecs (mp column/row split on
attention + FFN, vocab-parallel embedding) so one definition runs
single-chip or hybrid dp x mp x sharding under DistributedTrainStep;
bidirectional attention goes through F.scaled_dot_product_attention;
fp32 layernorm accumulation under bf16 autocast."""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed.parallel.mp_layers import sharded_constraint
from ..distributed.parallel.recompute import recompute
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, LayerNorm, Linear


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None   # default 4*hidden
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    hidden_act: str = "gelu"
    dropout: float = 0.0
    attention_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    use_recompute: bool = False
    #: remat policy when use_recompute — same vocabulary as GPTConfig:
    #: the reference granularities (selective/core_attn/full) plus the
    #: fleet.utils.RecomputeConfig policy names (dots_saveable/...)
    recompute_granularity: str = "core_attn"
    # ERNIE pretrains with sentence-order prediction (SOP); BERT-style
    # next-sentence prediction is the same 2-way head with other labels.
    with_pooler: bool = True
    #: fused MLM loss: gather the (<= max_predictions) masked positions
    #: and run transform+decode ONLY on them — the [B, S, vocab] logits
    #: never materialize and the head does ~15% of the dense FLOPs
    #: (standard max_predictions_per_seq pretraining contract)
    fused_mlm_loss: bool = False
    max_predictions: int = 80

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


from ._common import spec_linear as _linear


class ErnieEmbeddings(Layer):
    """word + position + token_type embeddings -> LayerNorm -> dropout."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        std = cfg.initializer_range
        self.word_embeddings = Embedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)))
        self.word_embeddings.weight.spec = P("mp", None)  # vocab-parallel
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)))
        self.position_embeddings.weight.spec = P()
        self.token_type_embeddings = Embedding(
            cfg.type_vocab_size, cfg.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)))
        self.token_type_embeddings.weight.spec = P()
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_epsilon)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, pos=None):
        b, s = input_ids.shape
        from .. import ops
        if pos is None:
            pos = ops.creation.arange(s, dtype="int32")
        elif not isinstance(pos, Tensor):
            pos = Tensor(pos)  # decode: [b, s] offsets from the KV cache
        x = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos)
        if token_type_ids is None:
            token_type_ids = ops.creation.zeros([b, s], dtype="int32")
        x = x + self.token_type_embeddings(token_type_ids)
        x = sharded_constraint(x, P(("dp", "sharding"), None, None))
        return self.dropout(self.layer_norm(x))


class ErnieSelfAttention(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        std = cfg.initializer_range
        self.qkv_proj = _linear(h, 3 * h, std, P(None, "mp"), P("mp"))
        self.out_proj = _linear(h, h, std / math.sqrt(2 * cfg.num_layers),
                                P("mp", None), P())
        self.dropout_p = cfg.attention_dropout

    def forward(self, x, attn_mask=None, cache=None, layer_idx=0,
                decode=False):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        qkv = sharded_constraint(qkv, P(("dp", "sharding"), None, "mp"))
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        if cache is not None:
            # incremental encoding (eval): cache this layer's k/v so a
            # growing sequence never recomputes the prefix — shared
            # choreography in generation/attention.py; ERNIE's prefill
            # attends bidirectionally, appended tokens attend the whole
            # cached prefix (+ causally within their own window)
            from ..generation.attention import cached_attention
            out, cache = cached_attention(
                q, k, v, cache, layer_idx, decode=decode, causal=False,
                attn_mask=attn_mask)
            return self.out_proj(out.reshape([b, s, h])), cache
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.dropout_p, training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class ErnieLayer(Layer):
    """Post-LN encoder block (BERT/ERNIE layout: residual -> LayerNorm)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        std = cfg.initializer_range
        self.attn = ErnieSelfAttention(cfg)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.fc1 = _linear(cfg.hidden_size, cfg.ffn_size, std,
                           P(None, "mp"), P("mp"))
        self.fc2 = _linear(cfg.ffn_size, cfg.hidden_size,
                           std / math.sqrt(2 * cfg.num_layers),
                           P("mp", None), P())
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.dropout = Dropout(cfg.dropout)
        self.act = cfg.hidden_act

    def forward(self, x, attn_mask=None, cache=None, layer_idx=0,
                decode=False):
        if cache is not None:
            a, cache = self.attn(x, attn_mask, cache=cache,
                                 layer_idx=layer_idx, decode=decode)
            x = self.ln1(x + a)
            h = self.fc1(x)
            h = F.gelu(h, approximate=True) if self.act == "gelu" \
                else F.relu(h)
            return self.ln2(x + self.fc2(h)), cache
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask)))
        h = self.fc1(x)
        h = F.gelu(h, approximate=True) if self.act == "gelu" else F.relu(h)
        return self.ln2(x + self.dropout(self.fc2(h)))


class ErniePooler(Layer):
    """[CLS] pooler: first-token hidden -> dense -> tanh."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.dense = _linear(cfg.hidden_size, cfg.hidden_size,
                             cfg.initializer_range, P(), P())

    def forward(self, x):
        from .. import ops
        return ops.math.tanh(self.dense(x[:, 0]))


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        self.layers = LayerList([ErnieLayer(cfg)
                                 for _ in range(cfg.num_layers)])
        self.pooler = ErniePooler(cfg) if cfg.with_pooler else None

    def forward(self, input_ids, token_type_ids=None, attn_mask=None,
                cache=None, use_cache=False, prompt_len=None,
                cache_max_len=None, cache_dtype=None):
        """Returns (sequence_output, pooled_output-or-None) — plus the
        KV cache as a third element under ``use_cache``/``cache``
        (incremental encoding: prefill fills the cache, later calls
        append tokens without recomputing the prefix).
        attn_mask: [b, s] 1/0 padding mask, or a broadcastable additive
        [b, 1, s, s] mask; converted to additive here."""
        if attn_mask is not None and len(attn_mask.shape) == 2:
            import jax.numpy as jnp
            m = attn_mask._data if isinstance(attn_mask, Tensor) \
                else attn_mask
            add = (1.0 - m.astype("float32")) * -1e9
            attn_mask = Tensor(add[:, None, None, :])
        if cache is not None or use_cache:
            return self._forward_cached(input_ids, token_type_ids,
                                        attn_mask, cache, prompt_len,
                                        cache_max_len, cache_dtype)
        x = self.embeddings(input_ids, token_type_ids)
        if self.cfg.use_recompute and self.training:
            from .gpt import _remat_policy
            policy = _remat_policy(self.cfg.recompute_granularity)
        else:
            policy = None
        for layer in self.layers:
            if policy is not None:
                x = recompute(layer, x, attn_mask, policy=policy)
            else:
                x = layer(x, attn_mask)
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled

    def _forward_cached(self, input_ids, token_type_ids, attn_mask,
                        cache, prompt_len, cache_max_len,
                        cache_dtype=None):
        """Incremental-encoding forward (eval only): returns
        (sequence_output, pooled-or-None, cache); ``pooled`` is filled
        on prefill only (decode windows don't contain CLS — it stays
        None there). NOTE ragged prefill
        (per-row ``prompt_len`` shorter than the padded width) is NOT
        masked here — bidirectional attention would see the pad keys;
        pass an explicit [b, s] attn_mask for padded prefill."""
        from ..generation.kv_cache import KVCache
        b, s = input_ids.shape
        decode = cache is not None
        if decode:
            x = self.embeddings(input_ids, token_type_ids,
                                pos=cache.positions(s))
        else:
            x = self.embeddings(input_ids, token_type_ids)
            max_len = int(cache_max_len
                          or self.cfg.max_position_embeddings)
            cache = KVCache.create(
                self.cfg.num_layers, b, max_len, self.cfg.num_heads,
                self.cfg.hidden_size // self.cfg.num_heads,
                dtype=x._data.dtype, cache_dtype=cache_dtype)
        for i, layer in enumerate(self.layers):
            x, cache = layer(x, attn_mask, cache=cache, layer_idx=i,
                             decode=decode)
        if decode:
            cache = cache.with_kv_len(cache.kv_len + s)
        else:
            cache = cache.with_kv_len(
                s if prompt_len is None else prompt_len)
        # pooled output only on prefill: on decode x holds just the
        # appended tokens, so x[:, 0] is NOT the CLS position — pooling
        # it would return a silently wrong sentence embedding
        pooled = self.pooler(x) if self.pooler is not None \
            and not decode else None
        return x, pooled, cache


class ErnieMLMHead(Layer):
    """transform(dense+act+LN) then decode against the tied word
    embedding (vocab-parallel matmul) + bias."""

    def __init__(self, cfg: ErnieConfig, embed: ErnieEmbeddings):
        super().__init__()
        self.transform = _linear(cfg.hidden_size, cfg.hidden_size,
                                 cfg.initializer_range, P(), P())
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_epsilon)
        self._embed_ref = [embed]
        from ..core.tensor import Parameter
        import numpy as np
        self.decoder_bias = Parameter(
            np.zeros([cfg.vocab_size], dtype=np.float32))
        self.decoder_bias.spec = P("mp")

    def forward(self, x):
        from .. import ops
        x = self.layer_norm(F.gelu(self.transform(x), approximate=True))
        wte = self._embed_ref[0].word_embeddings.weight
        logits = F.linear(x, ops.linalg.t(wte)) + self.decoder_bias
        return sharded_constraint(logits, P(("dp", "sharding"), None, "mp"))


class ErnieForPretraining(Layer):
    """MLM + sentence-order (2-way) pretraining heads, joint loss —
    the ERNIE/BERT pretraining objective (BASELINE config #3)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        if not cfg.with_pooler:
            raise ValueError("ErnieForPretraining needs the [CLS] pooler "
                             "for its sentence-order head; set "
                             "with_pooler=True")
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.mlm_head = ErnieMLMHead(cfg, self.ernie.embeddings)
        self.sop_head = _linear(cfg.hidden_size, 2,
                                cfg.initializer_range, P(), P())

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, attn_mask)
        if self.cfg.fused_mlm_loss:
            # ship the head params WITH the output (cloned while any
            # functional_call binding is live) so loss() sees traced
            # values and their gradients flow — same pattern as the
            # GPT fused LM loss
            head = self.mlm_head
            wp = (head.transform.weight.clone(),
                  head.transform.bias.clone(),
                  head.layer_norm.weight.clone(),
                  head.layer_norm.bias.clone(),
                  head.decoder_bias.clone(),
                  self.ernie.embeddings.word_embeddings.weight.clone())
            return seq, self.sop_head(pooled), wp
        return self.mlm_head(seq), self.sop_head(pooled)

    def _fused_mlm(self, h, y, tw, tb, lw, lb, db, wte):
        """Gathered-position MLM: select up to max_predictions masked
        slots per row, run transform+LN+decode on just those."""
        import jax

        b, s, hd = h.shape
        p = min(self.cfg.max_predictions, s)
        masked = y >= 0
        # stable argsort of (not masked): masked positions first, in
        # original order. Measured r5 against lax.top_k (0.473 vs
        # 0.481 e2e) and a cumsum+scatter compaction (0.474, and its
        # unfilled slots duplicate position 0) — the full sort WINS on
        # this shape; see experiments/ernie_fixed_cost_probe.py
        order = jnp.argsort(jnp.where(masked, 0, 1), axis=1,
                            stable=True)[:, :p]
        gh = jnp.take_along_axis(h, order[..., None], axis=1)
        gy = jnp.take_along_axis(y, order, axis=1)
        t = gh @ tw.astype(gh.dtype) + tb.astype(gh.dtype)
        c = 0.7978845608028654  # sqrt(2/pi)
        t = 0.5 * t * (1.0 + jnp.tanh(c * (t + 0.044715 * t ** 3)))
        mu = jnp.mean(t, axis=-1, keepdims=True)
        var = jnp.var(t, axis=-1, keepdims=True)
        t = (t - mu) / jnp.sqrt(var + self.cfg.layer_norm_epsilon)
        t = t * lw.astype(t.dtype) + lb.astype(t.dtype)
        logits = (t @ wte.T.astype(t.dtype)).astype(jnp.float32) + \
            db.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        safe = jnp.maximum(gy, 0)
        gold = jnp.take_along_axis(logits, safe[..., None],
                                   axis=-1)[..., 0]
        valid = (gy >= 0).astype(jnp.float32)
        return jnp.sum((lse - gold) * valid) /             jnp.maximum(jnp.sum(valid), 1.0)

    def loss(self, outputs, labels):
        """outputs = (mlm_logits, sop_logits) — or, under
        fused_mlm_loss, (seq_hidden, sop_logits, head_params);
        labels = (mlm_labels with ignore_index -100, sop_labels)."""
        mlm_labels, sop_labels = labels
        if self.cfg.fused_mlm_loss:
            seq, sop_logits, wp = outputs
            from ..core.tensor import dispatch
            mlm = dispatch(
                "fused_mlm_loss",
                lambda h, y, *w: self._fused_mlm(h, y, *w),
                (seq, mlm_labels) + tuple(wp), {})
        else:
            mlm_logits, sop_logits = outputs
            mlm = F.cross_entropy(
                mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
                mlm_labels.reshape([-1]), ignore_index=-100)
        sop = F.cross_entropy(sop_logits, sop_labels)
        return mlm + sop

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        n = self.num_params()
        att = 12 * self.cfg.num_layers * self.cfg.hidden_size * seq_len
        return 6 * n + att


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes: int = 2):
        super().__init__()
        if not cfg.with_pooler:
            raise ValueError("ErnieForSequenceClassification classifies "
                             "the pooled [CLS] state; set with_pooler=True")
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(cfg.dropout)
        self.classifier = _linear(cfg.hidden_size, num_classes,
                                  cfg.initializer_range, P(), P())

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attn_mask)
        return self.classifier(self.dropout(pooled))


# public ERNIE-3.0 / BERT sizes
CONFIGS = {
    "ernie-3.0-base": ErnieConfig(hidden_size=768, num_layers=12,
                                  num_heads=12),
    "ernie-3.0-medium": ErnieConfig(hidden_size=768, num_layers=6,
                                    num_heads=12),
    "ernie-3.0-xbase": ErnieConfig(hidden_size=1024, num_layers=20,
                                   num_heads=16),
    "bert-base": ErnieConfig(vocab_size=30522, hidden_size=768,
                             num_layers=12, num_heads=12,
                             max_position_embeddings=512,
                             type_vocab_size=2),
    "bert-large": ErnieConfig(vocab_size=30522, hidden_size=1024,
                              num_layers=24, num_heads=16,
                              max_position_embeddings=512,
                              type_vocab_size=2),
    "test-tiny": ErnieConfig(vocab_size=512, hidden_size=64, num_layers=2,
                             num_heads=4, max_position_embeddings=128),
}


def ernie(name: str = "ernie-3.0-base", **overrides) -> ErnieForPretraining:
    import dataclasses
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ErnieForPretraining(cfg)


def bert(name: str = "bert-base", **overrides) -> ErnieForPretraining:
    return ernie(name, **overrides)
