"""Shared model-building helpers for the model zoo."""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..nn import initializer as I
from ..nn.layers_common import Linear


def spec_linear(in_f, out_f, std, spec_w, spec_b=None, has_bias=True):
    """Linear with Normal(0, std) init and PartitionSpecs attached to its
    weights — the building block every model family shards with."""
    layer = Linear(in_f, out_f,
                   weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)),
                   bias_attr=None if has_bias else False)
    layer.weight.spec = spec_w
    if has_bias and layer.bias is not None:
        layer.bias.spec = spec_b if spec_b is not None else P()
    return layer
