"""DenseNet (≈ python/paddle/vision/models/densenet.py:
densenet121/161/169/201/264)."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D,
                                Conv2D, Linear, MaxPool2D, ReLU)
from ..ops.manipulation import concat, flatten


class DenseLayer(Layer):
    def __init__(self, c_in, growth_rate, bn_size):
        super().__init__()
        self.bn1 = BatchNorm2D(c_in)
        self.conv1 = Conv2D(c_in, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3,
                            padding=1, bias_attr=False)
        self.relu = ReLU()

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        return concat([x, out], axis=1)


class DenseBlock(Layer):
    def __init__(self, num_layers, c_in, growth_rate, bn_size):
        super().__init__()
        self.layers = Sequential(*[
            DenseLayer(c_in + i * growth_rate, growth_rate, bn_size)
            for i in range(num_layers)])

    def forward(self, x):
        return self.layers(x)


class Transition(Layer):
    def __init__(self, c_in, c_out):
        super().__init__()
        self.bn = BatchNorm2D(c_in)
        self.relu = ReLU()
        self.conv = Conv2D(c_in, c_out, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_CFGS = {
    121: (32, (6, 12, 24, 16), 64),
    161: (48, (6, 12, 36, 24), 96),
    169: (32, (6, 12, 32, 32), 64),
    201: (32, (6, 12, 48, 32), 64),
    264: (32, (6, 12, 64, 48), 64),
}


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, num_classes=1000,
                 with_pool=True):
        super().__init__()
        growth_rate, block_cfg, c0 = _CFGS[layers]
        self.conv1 = Conv2D(3, c0, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(c0)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        blocks = []
        c = c0
        for i, n in enumerate(block_cfg):
            blocks.append(DenseBlock(n, c, growth_rate, bn_size))
            c += n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Transition(c, c // 2))
                c //= 2
        self.blocks = Sequential(*blocks)
        self.bn_last = BatchNorm2D(c)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(c, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.relu(self.bn_last(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def densenet121(**kw):
    return DenseNet(121, **kw)


def densenet161(**kw):
    return DenseNet(161, **kw)


def densenet169(**kw):
    return DenseNet(169, **kw)


def densenet201(**kw):
    return DenseNet(201, **kw)


def densenet264(**kw):
    return DenseNet(264, **kw)
