"""GPT decoder-only transformer — the flagship pretraining model
(BASELINE.json config #4; capability analog of the reference's
auto_parallel_gpt_model.py test fixture and PaddleNLP GPT).

TPU-first: every weight carries a PartitionSpec (mp on qkv/ffn out-dims,
vocab on embedding) so the SAME model runs single-chip or hybrid
dp×mp×sharding under DistributedTrainStep; attention goes through
F.scaled_dot_product_attention (Pallas flash kernel for long seq);
bf16-friendly throughout (fp32 layernorm accumulation)."""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed.parallel.mp_layers import sharded_constraint
from ..distributed.parallel.recompute import recompute
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, LayerNorm, Linear


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None   # default 4*hidden
    max_position_embeddings: int = 1024
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_recompute: bool = False
    #: remat policy when use_recompute: "selective" saves matmul
    #: outputs (save_dots_no_batch — cheap backward, moderate memory),
    #: "full" saves nothing (max memory relief, ~1.3x trunk FLOPs).
    #: ≈ the reference's recompute_granularity (full/core_attn); also
    #: accepts the fleet.utils.RecomputeConfig policy names
    #: (dots_saveable / nothing_saveable / dots_with_no_batch_dims_saveable)
    recompute_granularity: str = "selective"
    #: fuse the LM head into the loss, scanned over sequence chunks so
    #: the [B, S, vocab] logits are never materialized — the dominant
    #: HBM cost at long seq (B16 s2048 logits alone are 3.3 GB bf16).
    #: forward() then returns the final hidden states; loss() applies
    #: the chunked head+CE (rematerialized per chunk in backward)
    fused_lm_loss: bool = False
    lm_loss_chunk: int = 256
    #: when a single chunk covers the whole sequence AND its fp32
    #: logits fit this many bytes, skip the per-chunk remat and save
    #: the logits for backward instead (measured faster: 35.3 vs
    #: 40.8 ms on the b16-s1024 head — experiments/lm_loss_head_probe
    #: .py); above the budget the remat scan keeps peak HBM at
    #: chunk*vocab regardless of batch
    lm_loss_save_logits_budget: int = 4 << 30
    tie_word_embeddings: bool = True
    sequence_parallel: bool = False   # shard seq dim over 'sp' +
    # ring attention (NEW vs the reference — SURVEY §5 long-context story)
    moe_num_experts: int = 0          # >0: MoE FFN over the 'ep' axis
    moe_gate: str = "gshard"
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


from ._common import spec_linear as _linear

#: recompute_granularity -> distributed.parallel.recompute policy name.
#: Keys cover both the reference's granularities (selective/core_attn/
#: full) and fleet.utils.RecomputeConfig's jax-named policies, so one
#: vocabulary works across model configs and train-step configs.
_REMAT_POLICY = {
    "selective": "save_dots_no_batch",
    "dots_with_no_batch_dims_saveable": "save_dots_no_batch",
    "core_attn": "save_dots",
    "dots_saveable": "save_dots",
    "full": "full",
    "nothing_saveable": "full",
}


def _remat_policy(granularity: str) -> str:
    """Resolve a recompute_granularity to the parallel.recompute policy
    name; a typo'd granularity ERRORS (silently training with a default
    policy would quietly ignore the user's memory/FLOPs intent)."""
    try:
        return _REMAT_POLICY[granularity]
    except KeyError:
        raise ValueError(
            f"unknown recompute_granularity {granularity!r}; one of "
            f"{sorted(_REMAT_POLICY)}") from None


class GPTAttention(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        std = cfg.initializer_range
        # fused qkv, out-dim mp-sharded (column parallel)
        self.qkv_proj = _linear(h, 3 * h, std, P(None, "mp"), P("mp"))
        # out proj, in-dim mp-sharded (row parallel)
        self.out_proj = _linear(h, h, std / math.sqrt(2 * cfg.num_layers),
                                P("mp", None), P())
        self.dropout_p = cfg.dropout
        self.sequence_parallel = cfg.sequence_parallel

    def forward(self, x, attn_mask=None, cache=None, layer_idx=0,
                decode=False):
        b, s, h = x.shape
        seq = "sp" if self.sequence_parallel else None
        qkv = self.qkv_proj(x)
        qkv = sharded_constraint(qkv, P(("dp", "sharding"), seq, "mp"))
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        if cache is not None:
            # generation path (eval) — shared cache choreography in
            # generation/attention.py; GPT attends causally on prefill
            if self.sequence_parallel:
                raise NotImplementedError(
                    "KV-cache generation under sequence_parallel ring "
                    "attention is not supported")
            from ..generation.attention import cached_attention
            out, cache = cached_attention(
                q, k, v, cache, layer_idx, decode=decode, causal=True,
                attn_mask=attn_mask)
            return self.out_proj(out.reshape([b, s, h])), cache
        if self.sequence_parallel:
            if attn_mask is not None:
                raise ValueError(
                    "sequence_parallel ring attention does not support an "
                    "explicit attn_mask (causal only)")
            if self.dropout_p > 0.0 and self.training:
                raise ValueError(
                    "sequence_parallel ring attention does not support "
                    "attention dropout; set cfg.dropout = 0")
            from ..core.tensor import dispatch
            from ..distributed.parallel.context_parallel import \
                ring_attention
            out = dispatch(
                "ring_attention",
                lambda q_, k_, v_: ring_attention(q_, k_, v_, causal=True),
                (q, k, v), {})
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=True,
                dropout_p=self.dropout_p, training=self.training)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        std = cfg.initializer_range
        self.fc1 = _linear(cfg.hidden_size, cfg.ffn_size, std,
                           P(None, "mp"), P("mp"))
        self.fc2 = _linear(cfg.ffn_size, cfg.hidden_size,
                           std / math.sqrt(2 * cfg.num_layers),
                           P("mp", None), P())
        self.dropout = Dropout(cfg.dropout)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
        if cfg.moe_num_experts > 0:
            from ..distributed.parallel.moe import MoEMLP
            self.mlp = MoEMLP(cfg.hidden_size, cfg.ffn_size,
                              num_experts=cfg.moe_num_experts,
                              gate=cfg.moe_gate,
                              capacity_factor=cfg.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(cfg)

    def forward(self, x, attn_mask=None, cache=None, layer_idx=0,
                decode=False):
        if cache is not None:
            a, cache = self.attn(self.ln1(x), attn_mask, cache=cache,
                                 layer_idx=layer_idx, decode=decode)
            x = x + a
            x = x + self.mlp(self.ln2(x))
            return x, cache
        x = x + self.attn(self.ln1(x), attn_mask)
        x = x + self.mlp(self.ln2(x))
        return x


class GPTEmbeddings(Layer):
    """Token + position embedding (+ dropout). Shared by the serial model
    and the pipeline 'pre' segment (≈ PaddleNLP GPTEmbeddings)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        std = cfg.initializer_range
        self.wte = Embedding(
            cfg.vocab_size, cfg.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)))
        self.wte.weight.spec = P("mp", None)  # vocab-parallel
        self.wpe = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, std)))
        self.wpe.weight.spec = P()
        self.drop = Dropout(cfg.dropout)
        self.sequence_parallel = cfg.sequence_parallel

    def forward(self, input_ids, pos=None):
        b, s = input_ids.shape
        from .. import ops
        if pos is None:
            pos = ops.creation.arange(s, dtype="int32")
        elif not isinstance(pos, Tensor):
            pos = Tensor(pos)  # decode: [b, s] offsets from the KV cache
        x = self.wte(input_ids) + self.wpe(pos)
        seq = "sp" if getattr(self, "sequence_parallel", False) else None
        x = sharded_constraint(x, P(("dp", "sharding"), seq, None))
        return self.drop(x)


def _lm_logits(x, head, wte_weight):
    """Final head dispatch (tied vs separate), with the output constraint.
    Shared by GPTForCausalLM and GPTHeadPipe."""
    if head is not None:
        logits = head(x)
    else:
        logits = F.linear(x, _transpose(wte_weight))
    return sharded_constraint(logits, P(("dp", "sharding"), None, "mp"))


class _AuxBlock(Layer):
    """Adapter returning (x, moe_aux) so the aux loss crosses the
    jax.checkpoint boundary as a RETURN VALUE (an attribute set inside
    the remat scope would leak its tracer)."""

    def __init__(self, block: "GPTBlock"):
        super().__init__()
        self.block = block

    def forward(self, x, attn_mask=None):
        out = self.block(x, attn_mask)
        # MoEMLP.forward always sets l_aux to a scalar Tensor
        return out, self.block.mlp.l_aux


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embed = GPTEmbeddings(cfg)
        self.blocks = LayerList([GPTBlock(cfg)
                                 for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size,
                              epsilon=cfg.layer_norm_epsilon)
        if cfg.moe_num_experts > 0:
            # plain list, NOT a LayerList: the adapters wrap blocks that
            # are already registered via self.blocks — registering them
            # again would duplicate every parameter in state_dict
            self._aux_blocks = [_AuxBlock(b) for b in self.blocks]
        #: total MoE aux loss of the last recompute-mode forward (same
        #: trace); None when the plain path ran (read l_aux attrs then)
        self._moe_aux = None

    def forward(self, input_ids, attn_mask=None, cache=None,
                use_cache=False, prompt_len=None, cache_max_len=None,
                cache_dtype=None):
        if cache is not None or use_cache:
            return self._forward_cached(input_ids, attn_mask, cache,
                                        prompt_len, cache_max_len,
                                        cache_dtype)
        x = self.embed(input_ids)
        self._moe_aux = None
        moe = self.cfg.moe_num_experts > 0
        if self.cfg.use_recompute and self.training:
            policy = _remat_policy(self.cfg.recompute_granularity)
            aux_total = None
            for i, block in enumerate(self.blocks):
                if moe:
                    x, aux = recompute(self._aux_blocks[i], x, attn_mask,
                                       policy=policy)
                    aux_total = aux if aux_total is None \
                        else aux_total + aux
                else:
                    x = recompute(block, x, attn_mask,
                                  policy=policy)
            self._moe_aux = aux_total
        else:
            for block in self.blocks:
                x = block(x, attn_mask)
        return self.ln_f(x)

    def _forward_cached(self, input_ids, attn_mask, cache, prompt_len,
                        cache_max_len, cache_dtype=None):
        """Generation forward (eval only): prefill creates + fills the
        KV cache (``cache=None``), decode consumes one. Returns
        (hidden, cache). ``prompt_len`` [b] marks each row's true
        length in a right-padded prompt; kv_len advances to it so the
        pad tail is invisible to (and overwritten by) decode steps.
        ``cache_dtype="int8"`` creates the quantized cache (values
        quantize in-trace at every write; decode dequantizes inside
        the kernel). Decode + ``prompt_len`` is the chunked-prefill
        window: s cache-writing positions whose tail may overhang the
        row's true length (the final padded chunk), so kv_len clamps
        to ``prompt_len`` — the overhang stays invisible to (and is
        overwritten by) later decode steps, exactly like prefill's pad
        tail."""
        from ..generation.kv_cache import KVCache
        import jax.numpy as jnp
        b, s = input_ids.shape
        decode = cache is not None
        if decode:
            x = self.embed(input_ids, pos=cache.positions(s))
        else:
            x = self.embed(input_ids)
            max_len = int(cache_max_len
                          or self.cfg.max_position_embeddings)
            cache = KVCache.create(
                self.cfg.num_layers, b, max_len, self.cfg.num_heads,
                self.cfg.hidden_size // self.cfg.num_heads,
                dtype=x._data.dtype, cache_dtype=cache_dtype)
        for i, block in enumerate(self.blocks):
            x, cache = block(x, attn_mask, cache=cache, layer_idx=i,
                             decode=decode)
        if decode:
            new_len = cache.kv_len + s
            if prompt_len is not None:
                plen = jnp.asarray(
                    prompt_len._data if isinstance(prompt_len, Tensor)
                    else prompt_len, jnp.int32)
                new_len = jnp.minimum(new_len, plen)
            cache = cache.with_kv_len(new_len)
        else:
            cache = cache.with_kv_len(
                s if prompt_len is None else prompt_len)
        return self.ln_f(x), cache


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = _linear(cfg.hidden_size, cfg.vocab_size,
                                   cfg.initializer_range, P(None, "mp"),
                                   has_bias=False)
        else:
            self.lm_head = None

    def forward(self, input_ids, attn_mask=None, cache=None,
                use_cache=False, prompt_len=None, cache_max_len=None,
                cache_dtype=None):
        if cache is not None or use_cache:
            return self._forward_cached(input_ids, attn_mask, cache,
                                        prompt_len, cache_max_len,
                                        cache_dtype)
        h = self.gpt(input_ids, attn_mask)
        if self.cfg.fused_lm_loss:
            # ship the head weight WITH the output (cloned while any
            # functional_call binding is live) so loss() sees the
            # traced/current value — reading self...weight there would
            # bake a stale constant into compiled train steps and drop
            # the head-weight gradient
            w = self.lm_head.weight if self.lm_head is not None \
                else self.gpt.embed.wte.weight
            return h, w.clone()
        return _lm_logits(h, self.lm_head,
                          self.gpt.embed.wte.weight)

    def _forward_cached(self, input_ids, attn_mask, cache, prompt_len,
                        cache_max_len, cache_dtype=None):
        """Generation forward: returns (logits, cache). Prefill returns
        next-token logits only ([b, 1, vocab], gathered at each row's
        last REAL position — the [b, s, vocab] prompt logits are never
        materialized); decode returns logits for all (1..8) new
        positions. Always the real LM head, even under fused_lm_loss
        (generation samples from logits, not a loss)."""
        import jax.numpy as jnp
        decode = cache is not None
        kv0 = cache.kv_len if decode else None
        h, cache = self.gpt(input_ids, attn_mask, cache=cache,
                            use_cache=True, prompt_len=prompt_len,
                            cache_max_len=cache_max_len,
                            cache_dtype=cache_dtype)
        if decode and prompt_len is not None:
            # chunked-prefill final window: gather each row's hidden at
            # its last REAL prompt position (global prompt_len - 1 ==
            # window-local prompt_len - 1 - kv_len-at-entry; the padded
            # tail past it is never sampled) → [b, 1, vocab], same
            # shape as a decode step's single-token logits
            from ..core.tensor import dispatch
            plen = jnp.asarray(
                prompt_len._data if isinstance(prompt_len, Tensor)
                else prompt_len, jnp.int32)
            idx = plen - 1 - kv0.astype(jnp.int32)
            h = dispatch(
                "gather_last_hidden",
                lambda hr, ir: jnp.take_along_axis(
                    hr, ir[:, None, None], axis=1),
                (h, idx), {}, differentiable=False)
        elif not decode:
            from ..core.tensor import dispatch
            b, s = input_ids.shape
            if prompt_len is None:
                h = h[:, s - 1:s]
            else:
                idx = jnp.asarray(
                    prompt_len._data if isinstance(prompt_len, Tensor)
                    else prompt_len, jnp.int32) - 1
                h = dispatch(
                    "gather_last_hidden",
                    lambda hr, ir: jnp.take_along_axis(
                        hr, ir[:, None, None], axis=1),
                    (h, idx), {}, differentiable=False)
        logits = _lm_logits(h, self.lm_head, self.gpt.embed.wte.weight)
        return logits, cache

    def generate(self, input_ids, max_new_tokens: int = 32, **kwargs):
        """Autoregressive decoding with the KV cache — see
        ``paddle_tpu.generation.generate`` for sampling options."""
        from ..generation.api import generate as _generate
        return _generate(self, input_ids, max_new_tokens, **kwargs)

    def _fused_loss(self, hidden, labels, w):
        """Chunked LM-head + cross-entropy: scan sequence chunks, each
        chunk's logits live only inside its (rematerialized) scan step.
        HBM for logits drops from S*V to chunk*V per microbatch.
        `w` is the head weight ([in, V] untied / [V, in] tied wte),
        passed as a traced operand so its gradient flows."""
        import jax

        h = hidden
        y = labels
        tied = self.lm_head is None
        hs = h[:, :-1, :]
        ys = y[:, 1:]
        b, s1, hd = hs.shape
        chunk = min(self.cfg.lm_loss_chunk, s1)
        n_chunks = -(-s1 // chunk)

        def chunk_ce(hc, yc):
            wmat = w.T if tied else w
            logits = (hc @ wmat.astype(hc.dtype)).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            yc_safe = jnp.maximum(yc, 0)
            gold = jnp.take_along_axis(
                logits, yc_safe[..., None], axis=-1)[..., 0]
            valid = (yc >= 0).astype(jnp.float32)
            return jnp.sum((lse - gold) * valid), jnp.sum(valid)

        vocab = w.shape[0] if tied else w.shape[-1]
        budget = self.cfg.lm_loss_save_logits_budget
        if n_chunks == 1 and b * s1 * vocab * 4 <= budget:
            # single chunk within the HBM budget: skip the scan AND the
            # remat — saving the logits for backward beats recomputing
            # the vocab matmul (measured: 35.3 vs 40.8 ms for the
            # b16-s1024 head, experiments/lm_loss_head_probe.py)
            total, count = chunk_ce(hs, ys)
            return total / jnp.maximum(count, 1.0)
        pad = n_chunks * chunk - s1
        hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
        ys = jnp.pad(ys, ((0, 0), (0, pad)), constant_values=-1)
        hs = hs.reshape(b, n_chunks, chunk, hd).transpose(1, 0, 2, 3)
        ys = ys.reshape(b, n_chunks, chunk).transpose(1, 0, 2)

        # NOTE r4: a middle tier (explicit bf16-logit residuals via
        # custom_vjp — see experiments/fused_ce_probe.py) wins the
        # isolated head by ~22% at b32/s2048 but LOSES end-to-end
        # (b32 MFU 0.468 -> 0.440, s2048 0.452 -> 0.428): the ~3.3 GB
        # of residuals resident across the trunk backward cost more in
        # scheduling/spill than the saved vocab-matmul remat. Measured
        # and reverted — over-budget configs keep the remat scan.
        def body(carry, xs):
            hc, yc = xs
            ssum, cnt = jax.checkpoint(chunk_ce)(hc, yc)
            return (carry[0] + ssum, carry[1] + cnt), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (hs, ys))
        return total / jnp.maximum(count, 1.0)

    def loss(self, logits, labels):
        """Shifted LM loss (mean over non-shifted tokens) + MoE aux loss
        when experts are active (read in the same trace as forward)."""
        fused = (self is not None
                 and getattr(self, "cfg", None) is not None
                 and self.cfg.fused_lm_loss)
        if fused:
            from ..core.tensor import dispatch
            hidden, w = logits  # forward returned (hidden, head_weight)
            # routed through dispatch so the eager tape records it and
            # the head weight is a differentiable operand
            ce = dispatch("fused_lm_loss",
                          lambda h, y, wv: self._fused_loss(h, y, wv),
                          (hidden, labels, w), {})
        else:
            shifted = logits[:, :-1, :]
            targets = labels[:, 1:]
            ce = F.cross_entropy(
                shifted.reshape([-1, shifted.shape[-1]]),
                targets.reshape([-1]))
        if self is not None and getattr(self, "cfg", None) is not None \
                and self.cfg.moe_num_experts > 0:
            carried = getattr(self.gpt, "_moe_aux", None)
            if carried is not None:  # recompute path: aux was returned
                aux = carried
            else:
                from ..distributed.parallel.moe import aux_loss
                aux = aux_loss(self)
            ce = ce + self.cfg.moe_aux_weight * aux
        return ce

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        """Approximate training FLOPs/token (6N + attention term)."""
        n = self.num_params()
        att = 12 * self.cfg.num_layers * self.cfg.hidden_size * seq_len
        return 6 * n + att


def _transpose(w):
    from .. import ops
    return ops.linalg.t(w)


# convenience configs (≈ PaddleNLP gpt2 sizes; 6.7B = BASELINE config #4)
CONFIGS = {
    "gpt2-small": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-medium": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": GPTConfig(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                           max_position_embeddings=2048),
    "test-tiny": GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                           num_heads=4, max_position_embeddings=128),
    # draft companion for speculative decoding tests/bench: same vocab
    # and position table as test-tiny (a draft LM must share both), a
    # quarter of the compute — the KVCache layout class is identical
    "test-tiny-draft": GPTConfig(vocab_size=512, hidden_size=32,
                                 num_layers=1, num_heads=2,
                                 max_position_embeddings=128),
}


def gpt(name: str = "gpt2-small", **overrides) -> GPTForCausalLM:
    import dataclasses
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return GPTForCausalLM(cfg)


# ---------------------------------------------------------------- pipeline
GPTEmbeddingPipe = GPTEmbeddings  # the 'pre' segment IS the embedding


class GPTHeadPipe(Layer):
    """'post' segment: final norm + (tied) LM head. Holds an unregistered
    reference to the embedding for weight tying (the SharedLayerDesc
    analog — values flow through the embedding's own name under
    functional_call)."""

    def __init__(self, cfg: GPTConfig, embed: Optional[GPTEmbeddings]):
        super().__init__()
        self.ln_f = LayerNorm(cfg.hidden_size,
                              epsilon=cfg.layer_norm_epsilon)
        self._embed_ref = [embed]
        if embed is None:
            self.head = _linear(cfg.hidden_size, cfg.vocab_size,
                                cfg.initializer_range, P(None, "mp"),
                                has_bias=False)
        else:
            self.head = None

    def forward(self, x):
        x = self.ln_f(x)
        wte = self._embed_ref[0].wte.weight if self.head is None else None
        return _lm_logits(x, self.head, wte)


def gpt_pipe(name: str = "gpt2-small", num_stages: Optional[int] = None,
             num_microbatches: Optional[int] = None, interleave: int = 1,
             seg_sizes=None, **overrides):
    """Pipeline-parallel GPT: [embed | blocks... | norm+head] as a
    PipelineLayer over the 'pp' mesh axis (≈ GPTForCausalLMPipe)."""
    import dataclasses
    from ..distributed.parallel.pipeline import PipelineLayer
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    if cfg.moe_num_experts > 0:
        # per-stage aux-loss collection across the pp shard_map stages is
        # not wired yet; fail loudly rather than silently dropping the
        # load-balancing loss
        raise NotImplementedError(
            "MoE inside the pipeline-parallel GPT is not supported yet; "
            "use the serial gpt() model with ep/dp/mp axes instead")
    embed = GPTEmbeddingPipe(cfg)
    layers = ([embed] + [GPTBlock(cfg) for _ in range(cfg.num_layers)]
              + [GPTHeadPipe(cfg, embed if cfg.tie_word_embeddings
                             else None)])
    model = PipelineLayer(
        layers, num_stages=num_stages,
        num_microbatches=num_microbatches,
        use_recompute=cfg.use_recompute, interleave=interleave,
        seg_sizes=seg_sizes,
        loss_fn=lambda logits, labels: GPTForCausalLM.loss(
            None, logits, labels))
    model.cfg = cfg
    return model
