"""ShuffleNetV2 (≈ python/paddle/vision/models/shufflenetv2.py).
Channel shuffle is a reshape/transpose pair — free for XLA."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Linear, MaxPool2D, ReLU)
from ..ops.manipulation import concat, flatten, reshape, transpose


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _conv_bn(c_in, c_out, k, stride=1, groups=1, act=True):
    layers = [Conv2D(c_in, c_out, k, stride=stride, padding=k // 2,
                     groups=groups, bias_attr=False), BatchNorm2D(c_out)]
    if act:
        layers.append(ReLU())
    return Sequential(*layers)


class ShuffleUnit(Layer):
    def __init__(self, c_in, c_out, stride):
        super().__init__()
        self.stride = stride
        branch_c = c_out // 2
        if stride > 1:
            self.branch1 = Sequential(
                _conv_bn(c_in, c_in, 3, stride=stride, groups=c_in,
                         act=False),
                _conv_bn(c_in, branch_c, 1))
            b2_in = c_in
        else:
            self.branch1 = None
            b2_in = c_in // 2
        self.branch2 = Sequential(
            _conv_bn(b2_in, branch_c, 1),
            _conv_bn(branch_c, branch_c, 3, stride=stride, groups=branch_c,
                     act=False),
            _conv_bn(branch_c, branch_c, 1))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = _STAGE_OUT[scale]
        self.conv1 = _conv_bn(3, cfg[0], 3, stride=2)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        stages = []
        c_in = cfg[0]
        for c_out, repeats in zip(cfg[1:4], (4, 8, 4)):
            units = [ShuffleUnit(c_in, c_out, 2)]
            units += [ShuffleUnit(c_out, c_out, 1)
                      for _ in range(repeats - 1)]
            stages.append(Sequential(*units))
            c_in = c_out
        self.stages = Sequential(*stages)
        self.conv_last = _conv_bn(c_in, cfg[4], 1)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(cfg[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(scale=2.0, **kw)
