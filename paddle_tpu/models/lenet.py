"""LeNet (≈ python/paddle/vision/models/lenet.py) — BASELINE config #1."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import Conv2D, Linear, MaxPool2D, ReLU


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        from .. import ops
        x = ops.manipulation.flatten(x, 1)
        return self.fc(x)
