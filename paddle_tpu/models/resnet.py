"""ResNet family (≈ python/paddle/vision/models/resnet.py — the reference
ships resnet18/34/50/101/152 with BasicBlock/BottleneckBlock). NCHW API
for parity; `data_format="NHWC"` runs the whole trunk channels-last
(input transposed once at entry), the layout the reference plumbs per
conv (nn/functional/conv.py data_format) and the one TPU convs prefer
— see BASELINE.md for the measured NCHW-vs-NHWC comparison."""
from __future__ import annotations

from ..nn import functional as F
from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Linear, MaxPool2D, ReLU)


# ablation knob (experiments/fused_bn_probe.py): route the 3x3 of fused
# blocks through the Pallas window kernel (True) or XLA conv (False)
_PALLAS3X3 = True


def _stride0(conv):
    s = conv.stride
    return s[0] if isinstance(s, (tuple, list)) else s


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False, **df)
        self.bn1 = BatchNorm2D(planes, **df)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                            **df)
        self.bn2 = BatchNorm2D(planes, **df)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, data_format="NCHW",
                 fused_bn=False):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False, **df)
        self.bn1 = BatchNorm2D(width, **df)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False, **df)
        self.bn2 = BatchNorm2D(width, **df)
        self.conv3 = Conv2D(width, planes * 4, 1, bias_attr=False, **df)
        self.bn3 = BatchNorm2D(planes * 4, **df)
        self.downsample = downsample
        self.relu = ReLU()
        self.data_format = data_format
        self.fused_bn = fused_bn

    def forward(self, x):
        if (self.fused_bn and self.training and self.data_format == "NHWC"
                and not self.bn1.use_global_stats):
            return self._forward_fused(x)
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def _forward_fused(self, x):
        """Training-mode fused path (NHWC): the 1x1 convs run as Pallas
        matmuls that compute their output's BN statistics in the same
        HBM pass (conv1, conv3, downsample) and apply the previous BN +
        ReLU on the fly while reading their input (conv3) — the analog
        of the reference's resnet_unit_op / fused_bn_add_activation
        fusion (see kernels/fused_resnet.py for the roofline argument).
        Numerics match the unfused path within bf16 rounding; running
        stats update identically."""
        from ..nn.functional.fused_conv import (bn_apply_relu,
                                                bn_center_apply,
                                                bn_center_apply_relu_add,
                                                bn_fold,
                                                bn_moments, conv1x1_bn_stats,
                                                bn_relu_conv1x1_bn_stats,
                                                bn_relu_conv3x3_bn_stats)
        y1, m1, v1 = conv1x1_bn_stats(x, self.conv1.weight)
        self.bn1._update_running(m1, v1)
        s1, t1 = bn_fold(self.bn1.weight, self.bn1.bias, m1, v1,
                         self.bn1.epsilon)
        from ..kernels.fused_resnet import conv3x3_vmem_ok
        stride2 = _stride0(self.conv2)
        h, wd, cw = y1.shape[1], y1.shape[2], y1.shape[3]
        co = self.conv2.weight.shape[0]
        itemsize = y1.data.dtype.itemsize if hasattr(y1, "data") \
            else y1.dtype.itemsize
        pallas3x3 = (_PALLAS3X3 and stride2 == 1 and self.conv2.groups == 1
                     and conv3x3_vmem_ok(h, wd, cw, co, itemsize))
        if pallas3x3:
            # bn1-apply + relu + 3x3 conv + bn2 stats in one kernel: the
            # normalized activation never exists in HBM
            y2, m2, v2 = bn_relu_conv3x3_bn_stats(
                y1, s1, t1, self.conv2.weight)
        else:
            a1 = bn_apply_relu(y1, s1, t1)
            y2 = self.conv2(a1)
            m2, v2 = bn_moments(y2)
        self.bn2._update_running(m2, v2)
        s2, t2 = bn_fold(self.bn2.weight, self.bn2.bias, m2, v2,
                         self.bn2.epsilon)
        y3, m3, v3 = bn_relu_conv1x1_bn_stats(y2, s2, t2, self.conv3.weight)
        self.bn3._update_running(m3, v3)
        # epilogue applies run CENTERED (mean passed explicitly, beta
        # raw): only bn_fold's scale output is consumed, so the gamma
        # gradient is rsqrt(var+eps) * dscale with no cancelling
        # dscale - mean*dshift subtraction (see bn_center_apply*)
        s3, _ = bn_fold(self.bn3.weight, self.bn3.bias, m3, v3,
                        self.bn3.epsilon)
        if self.downsample is not None:
            dsconv, dsbn = self.downsample[0], self.downsample[1]
            yd, md, vd = conv1x1_bn_stats(x, dsconv.weight,
                                          stride=_stride0(dsconv))
            dsbn._update_running(md, vd)
            sd, _ = bn_fold(dsbn.weight, dsbn.bias, md, vd, dsbn.epsilon)
            identity = bn_center_apply(yd, md, sd, dsbn.bias)
        else:
            identity = x
        return bn_center_apply_relu_add(y3, m3, s3, self.bn3.bias, identity)


class ResNet(Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 groups=1, width_per_group=64, data_format="NCHW",
                 stem_space_to_depth=False, fused_bn=False,
                 recompute_stages=()):
        super().__init__()
        if not issubclass(block, BottleneckBlock) and \
                (groups != 1 or width_per_group != 64):
            raise ValueError(
                "groups/width_per_group require BottleneckBlock "
                "(resnet50+); BasicBlock variants do not support them")
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError(f"data_format must be NCHW or NHWC, "
                             f"got {data_format!r}")
        self.inplanes = 64
        self.groups = groups
        self.base_width = width_per_group
        self.data_format = data_format
        self.stem_space_to_depth = stem_space_to_depth
        self.fused_bn = fused_bn and issubclass(block, BottleneckBlock)
        # per-stage remat (1-4): re-run the stage's blocks in backward
        # instead of saving their intermediates — trades spare MXU time
        # for HBM traffic on the bandwidth-bound early stages. Engages
        # only under jit tracing (TrainStep), where BN running stats
        # are frozen by design anyway; eager forward runs the normal
        # path so running stats keep updating.
        self.recompute_stages = tuple(recompute_stages)
        bad = [s for s in self.recompute_stages if s not in (1, 2, 3, 4)]
        if bad:
            raise ValueError(
                f"recompute_stages entries must be stage numbers 1-4 "
                f"(1-indexed: layer1..layer4), got {bad}")
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False,
                            **df)
        self.bn1 = BatchNorm2D(64, **df)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1, **df)
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)
        self.num_classes = num_classes

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        df = dict(data_format=self.data_format)
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False, **df),
                BatchNorm2D(planes * block.expansion, **df))
        kw = dict(df)
        if issubclass(block, BottleneckBlock):
            kw.update(groups=self.groups, base_width=self.base_width,
                      fused_bn=self.fused_bn)
        layers = [block(self.inplanes, planes, stride, downsample, **kw)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **kw))
        return Sequential(*layers)

    def _stem_s2d(self, x):
        """Space-to-depth stem: the 7x7/s2 conv on 3 input channels
        uses ~3/128 of the MXU contraction depth. Repack 2x2 pixel
        blocks into channels (C 3->12) and run the numerically-equal
        4x4/s1 conv built from the same 7x7 weight (zero-padded to 8x8
        at the front). The MLPerf-TPU trick; weights stay in the
        reference 7x7 layout so checkpoints are unaffected."""
        w = self.conv1.weight                               # [O, 3, 7, 7]
        o = w.shape[0]
        wp = F.pad(w, [1, 0, 1, 0], data_format="NCHW")     # [O, 3, 8, 8]
        wp = wp.reshape([o, 3, 4, 2, 4, 2])                 # O I mh rh mw rw
        wp = wp.transpose([0, 3, 5, 1, 2, 4]).reshape([o, 12, 4, 4])
        if self.data_format == "NHWC":
            n, h, wd, c = x.shape
        else:
            n, c, h, wd = x.shape
        if h % 2 or wd % 2:
            raise ValueError(
                f"stem_space_to_depth requires even input H/W, got "
                f"{h}x{wd}; use the default stem for odd sizes")
        if self.data_format == "NHWC":
            xp = x.reshape([n, h // 2, 2, wd // 2, 2, c])
            xp = xp.transpose([0, 1, 3, 2, 4, 5]).reshape(
                [n, h // 2, wd // 2, 4 * c])
        else:
            xp = x.reshape([n, c, h // 2, 2, wd // 2, 2])
            xp = xp.transpose([0, 3, 5, 1, 2, 4]).reshape(
                [n, 4 * c, h // 2, wd // 2])
        # bias present after fuse_conv_bn folding; None otherwise
        return F.conv2d(xp, wp, bias=getattr(self.conv1, "bias", None),
                        stride=1, padding=[2, 1, 2, 1],
                        data_format=self.data_format)

    def forward(self, x):
        if self.data_format == "NHWC" and x.shape[-1] != 3:
            # accept NCHW input for API compat; one transpose at entry
            if x.shape[1] != 3:
                raise ValueError(
                    f"NHWC ResNet expects input [N,H,W,3] or NCHW "
                    f"[N,3,H,W]; got shape {list(x.shape)}")
            x = x.transpose([0, 2, 3, 1])
        if self.stem_space_to_depth:
            x = self.maxpool(self.relu(self.bn1(self._stem_s2d(x))))
            return self._trunk(x)
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        return self._trunk(x)

    def _trunk(self, x):
        import jax as _jax
        data = x.data if hasattr(x, "data") else x
        traced = isinstance(data, _jax.core.Tracer)
        if self.training and self.recompute_stages and traced:
            from ..distributed.parallel.recompute import recompute
            stages = (self.layer1, self.layer2, self.layer3, self.layer4)
            for i, stage in enumerate(stages, 1):
                if i in self.recompute_stages:
                    for blk in stage:
                        x = recompute(blk, x)
                else:
                    x = stage(x)
        else:
            x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from .. import ops
            x = ops.manipulation.flatten(x, 1)
            x = self.fc(x)
        return x


_CFGS = {
    18: (BasicBlock, [2, 2, 2, 2]),
    34: (BasicBlock, [3, 4, 6, 3]),
    50: (BottleneckBlock, [3, 4, 6, 3]),
    101: (BottleneckBlock, [3, 4, 23, 3]),
    152: (BottleneckBlock, [3, 8, 36, 3]),
}


def resnet18(**kw):
    return ResNet(*_CFGS[18], **kw)


def resnet34(**kw):
    return ResNet(*_CFGS[34], **kw)


def resnet50(**kw):
    return ResNet(*_CFGS[50], **kw)


def resnet101(**kw):
    return ResNet(*_CFGS[101], **kw)


def resnet152(**kw):
    return ResNet(*_CFGS[152], **kw)


def resnext50_32x4d(**kw):
    return ResNet(*_CFGS[50], groups=32, width_per_group=4, **kw)


def resnext101_32x4d(**kw):
    return ResNet(*_CFGS[101], groups=32, width_per_group=4, **kw)


def resnext101_64x4d(**kw):
    return ResNet(*_CFGS[101], groups=64, width_per_group=4, **kw)


def resnext152_64x4d(**kw):
    return ResNet(*_CFGS[152], groups=64, width_per_group=4, **kw)


def wide_resnet50_2(**kw):
    return ResNet(*_CFGS[50], width_per_group=128, **kw)


def wide_resnet101_2(**kw):
    return ResNet(*_CFGS[101], width_per_group=128, **kw)
