"""ResNet family (≈ python/paddle/vision/models/resnet.py — the reference
ships resnet18/34/50/101/152 with BasicBlock/BottleneckBlock). NCHW API
for parity; XLA:TPU's layout assignment converts to its preferred layout
internally."""
from __future__ import annotations

from ..nn import functional as F
from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Linear, MaxPool2D, ReLU)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, planes * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(planes * 4)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 groups=1, width_per_group=64):
        super().__init__()
        if not issubclass(block, BottleneckBlock) and \
                (groups != 1 or width_per_group != 64):
            raise ValueError(
                "groups/width_per_group require BottleneckBlock "
                "(resnet50+); BasicBlock variants do not support them")
        self.inplanes = 64
        self.groups = groups
        self.base_width = width_per_group
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)
        self.num_classes = num_classes

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        kw = {}
        if issubclass(block, BottleneckBlock):
            kw = dict(groups=self.groups, base_width=self.base_width)
        layers = [block(self.inplanes, planes, stride, downsample, **kw)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **kw))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from .. import ops
            x = ops.manipulation.flatten(x, 1)
            x = self.fc(x)
        return x


_CFGS = {
    18: (BasicBlock, [2, 2, 2, 2]),
    34: (BasicBlock, [3, 4, 6, 3]),
    50: (BottleneckBlock, [3, 4, 6, 3]),
    101: (BottleneckBlock, [3, 4, 23, 3]),
    152: (BottleneckBlock, [3, 8, 36, 3]),
}


def resnet18(**kw):
    return ResNet(*_CFGS[18], **kw)


def resnet34(**kw):
    return ResNet(*_CFGS[34], **kw)


def resnet50(**kw):
    return ResNet(*_CFGS[50], **kw)


def resnet101(**kw):
    return ResNet(*_CFGS[101], **kw)


def resnet152(**kw):
    return ResNet(*_CFGS[152], **kw)


def resnext50_32x4d(**kw):
    return ResNet(*_CFGS[50], groups=32, width_per_group=4, **kw)


def resnext101_32x4d(**kw):
    return ResNet(*_CFGS[101], groups=32, width_per_group=4, **kw)


def resnext101_64x4d(**kw):
    return ResNet(*_CFGS[101], groups=64, width_per_group=4, **kw)


def resnext152_64x4d(**kw):
    return ResNet(*_CFGS[152], groups=64, width_per_group=4, **kw)


def wide_resnet50_2(**kw):
    return ResNet(*_CFGS[50], width_per_group=128, **kw)


def wide_resnet101_2(**kw):
    return ResNet(*_CFGS[101], width_per_group=128, **kw)
