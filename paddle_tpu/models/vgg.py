"""VGG family (≈ python/paddle/vision/models/vgg.py: vgg11/13/16/19
with optional batch norm)."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Dropout, Linear, MaxPool2D, ReLU)
from ..ops.manipulation import flatten

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
         512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg, batch_norm):
    layers = []
    c_in = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, stride=2))
        else:
            layers.append(Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            c_in = v
    return Sequential(*layers)


class VGG(Layer):
    def __init__(self, features, num_classes=1000, with_pool=True,
                 dropout=0.5):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(7)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(dropout),
                Linear(4096, 4096), ReLU(), Dropout(dropout),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _vgg(depth, batch_norm=False, **kw):
    return VGG(_make_features(_CFGS[depth], batch_norm), **kw)


def vgg11(batch_norm=False, **kw):
    return _vgg(11, batch_norm, **kw)


def vgg13(batch_norm=False, **kw):
    return _vgg(13, batch_norm, **kw)


def vgg16(batch_norm=False, **kw):
    return _vgg(16, batch_norm, **kw)


def vgg19(batch_norm=False, **kw):
    return _vgg(19, batch_norm, **kw)
