"""SqueezeNet 1.0/1.1 (≈ python/paddle/vision/models/squeezenet.py)."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, Conv2D, Dropout,
                                MaxPool2D, ReLU)
from ..ops.manipulation import concat, flatten


class Fire(Layer):
    def __init__(self, c_in, squeeze, e1x1, e3x3):
        super().__init__()
        self.squeeze = Conv2D(c_in, squeeze, 1)
        self.relu = ReLU()
        self.expand1 = Conv2D(squeeze, e1x1, 1)
        self.expand3 = Conv2D(squeeze, e3x3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1(x)),
                       self.relu(self.expand3(x))], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2), Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5), Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return flatten(x, 1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)
