"""PP-YOLOE-style anchor-free detector (capability analog of
PaddleDetection's PP-YOLOE, the vision config in BASELINE.json #5;
reference building blocks: RepVGG-style re-parameterizable convs,
CSPResNet backbone, PAN neck, ET-head with distribution focal loss).

TPU-first choices: every compute path is static-shape (per-level
feature maps, fixed top-k in the assigner) so the whole train step
jits; box decode + NMS run as host numpy at eval time (dynamic-shape
output), matching how the reference exports NMS to a CPU op.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.container import LayerList, Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D,
                                Silu)

__all__ = ["PPYOLOE", "ppyoloe_s", "ppyoloe_m", "RepVggBlock",
           "CSPResNet", "CustomPAN", "PPYOLOEHead"]


def _raw(x):
    return x._data if isinstance(x, Tensor) else x


class ConvBNAct(Layer):
    def __init__(self, c_in, c_out, k=3, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(c_out)
        self.act = Silu() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class RepVggBlock(Layer):
    """Train-time 3x3 + 1x1 branches; fuse() re-parameterizes into one
    3x3 conv for deployment (RepVGG trick the reference uses)."""

    def __init__(self, c_in, c_out):
        super().__init__()
        self.conv1 = ConvBNAct(c_in, c_out, 3, act=False)
        self.conv2 = ConvBNAct(c_in, c_out, 1, act=False)
        self.act = Silu()
        self._fused: Optional[Conv2D] = None

    def forward(self, x):
        if self._fused is not None:
            return self.act(self._fused(x))
        return self.act(self.conv1(x) + self.conv2(x))

    def fuse(self):
        """Merge both conv+bn branches into a single 3x3 conv."""
        def fold(cb: ConvBNAct, pad_to_3x3: bool):
            w = np.asarray(_raw(cb.conv.weight))
            bn = cb.bn
            gamma = np.asarray(_raw(bn.weight))
            beta = np.asarray(_raw(bn.bias))
            mean = np.asarray(_raw(bn._mean))
            var = np.asarray(_raw(bn._variance))
            std = np.sqrt(var + bn.epsilon)
            w = w * (gamma / std)[:, None, None, None]
            b = beta - gamma * mean / std
            if pad_to_3x3 and w.shape[-1] == 1:
                w = np.pad(w, [(0, 0), (0, 0), (1, 1), (1, 1)])
            return w, b

        w3, b3 = fold(self.conv1, False)
        w1, b1 = fold(self.conv2, True)
        fused = Conv2D(self.conv1.conv.in_channels,
                       self.conv1.conv.out_channels, 3, padding=1)
        fused.weight._data = jnp.asarray(w3 + w1)
        fused.bias._data = jnp.asarray(b3 + b1)
        self._fused = fused
        return self


class CSPResStage(Layer):
    def __init__(self, c_in, c_out, n_blocks, stride=2):
        super().__init__()
        self.down = ConvBNAct(c_in, c_out, 3, stride=stride) \
            if stride > 1 or c_in != c_out else None
        mid = c_out // 2
        self.conv1 = ConvBNAct(c_out, mid, 1)
        self.conv2 = ConvBNAct(c_out, mid, 1)
        self.blocks = Sequential(*[RepVggBlock(mid, mid)
                                   for _ in range(n_blocks)])
        self.conv3 = ConvBNAct(mid * 2, c_out, 1)

    def forward(self, x):
        if self.down is not None:
            x = self.down(x)
        y1 = self.conv1(x)
        y2 = self.blocks(self.conv2(x))
        from ..ops.manipulation import concat
        return self.conv3(concat([y1, y2], axis=1))


class CSPResNet(Layer):
    """Backbone returning strides 8/16/32 features."""

    def __init__(self, widths=(64, 128, 256, 512),
                 depths=(1, 2, 2), width_mult=1.0, depth_mult=1.0):
        super().__init__()
        w = [max(8, int(c * width_mult)) for c in widths]
        d = [max(1, round(n * depth_mult)) for n in depths]
        self.stem = Sequential(
            ConvBNAct(3, w[0] // 2, 3, stride=2),
            ConvBNAct(w[0] // 2, w[0], 3, stride=2))  # stride 4
        self.stage1 = CSPResStage(w[0], w[1], d[0])   # stride 8
        self.stage2 = CSPResStage(w[1], w[2], d[1])   # stride 16
        self.stage3 = CSPResStage(w[2], w[3], d[2])   # stride 32
        self.out_channels = (w[1], w[2], w[3])

    def forward(self, x):
        x = self.stem(x)
        c2 = self.stage1(x)
        c3 = self.stage2(c2)
        c4 = self.stage3(c3)
        return c2, c3, c4  # strides 8, 16, 32


def _upsample2x(x):
    from ..nn.functional.common import interpolate
    return interpolate(x, scale_factor=2, mode="nearest")


class CustomPAN(Layer):
    """PAN-FPN neck: top-down + bottom-up CSP fusion."""

    def __init__(self, in_channels: Tuple[int, int, int], width=1.0):
        super().__init__()
        c3, c4, c5 = in_channels
        m = lambda c: max(8, int(c * width))
        self.reduce5 = ConvBNAct(c5, m(c4), 1)
        self.td4 = CSPResStage(c4 + m(c4), m(c4), 1, stride=1)
        self.reduce4 = ConvBNAct(m(c4), m(c3), 1)
        self.td3 = CSPResStage(c3 + m(c3), m(c3), 1, stride=1)
        self.down3 = ConvBNAct(m(c3), m(c3), 3, stride=2)
        self.bu4 = CSPResStage(m(c3) + m(c4), m(c4), 1, stride=1)
        self.down4 = ConvBNAct(m(c4), m(c4), 3, stride=2)
        self.bu5 = CSPResStage(m(c4) + m(c4), m(c4), 1, stride=1)
        self.out_channels = (m(c3), m(c4), m(c4))

    def forward(self, feats):
        from ..ops.manipulation import concat
        c3, c4, c5 = feats
        p5 = self.reduce5(c5)
        p4 = self.td4(concat([c4, _upsample2x(p5)], axis=1))
        p4r = self.reduce4(p4)
        p3 = self.td3(concat([c3, _upsample2x(p4r)], axis=1))
        n4 = self.bu4(concat([self.down3(p3), p4], axis=1))
        n5 = self.bu5(concat([self.down4(n4), p5], axis=1))
        return p3, n4, n5


class ESEAttn(Layer):
    def __init__(self, c):
        super().__init__()
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Conv2D(c, c, 1)
        self.conv = ConvBNAct(c, c, 1)

    def forward(self, x):
        gate = F.sigmoid(self.fc(self.pool(x)))
        return self.conv(x * gate)


class PPYOLOEHead(Layer):
    """Anchor-free ET-head: per-level cls logits + DFL regression."""

    def __init__(self, in_channels: Sequence[int], num_classes: int,
                 reg_max: int = 16,
                 strides: Sequence[int] = (8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.strides = tuple(strides)
        self.stem_cls = LayerList([ESEAttn(c) for c in in_channels])
        self.stem_reg = LayerList([ESEAttn(c) for c in in_channels])
        self.pred_cls = LayerList([
            Conv2D(c, num_classes, 3, padding=1) for c in in_channels])
        self.pred_reg = LayerList([
            Conv2D(c, 4 * (reg_max + 1), 3, padding=1)
            for c in in_channels])

    def forward(self, feats):
        """Returns per-level (cls_logits [B,HW,C], reg_logits
        [B,HW,4,reg_max+1], anchor centers [HW,2], stride)."""
        from ..ops.manipulation import reshape, transpose
        outs = []
        for i, x in enumerate(feats):
            cls_feat = self.stem_cls[i](x) + x
            reg_feat = self.stem_reg[i](x)
            cls = self.pred_cls[i](cls_feat)
            reg = self.pred_reg[i](reg_feat)
            b = x.shape[0]
            h, w = x.shape[2], x.shape[3]
            cls = transpose(reshape(cls, [b, self.num_classes, h * w]),
                            [0, 2, 1])
            reg = reshape(
                transpose(reshape(reg, [b, 4 * (self.reg_max + 1),
                                        h * w]), [0, 2, 1]),
                [b, h * w, 4, self.reg_max + 1])
            ys, xs = jnp.meshgrid(jnp.arange(h) + 0.5,
                                  jnp.arange(w) + 0.5, indexing="ij")
            centers = jnp.stack([xs.reshape(-1), ys.reshape(-1)], -1) \
                * self.strides[i]
            outs.append((cls, reg, centers, self.strides[i]))
        return outs


def _dfl_expect(reg_logits):
    """[..., 4, reg_max+1] logits -> expected ltrb distances."""
    n = reg_logits.shape[-1]
    probs = jax.nn.softmax(reg_logits, axis=-1)
    return (probs * jnp.arange(n, dtype=probs.dtype)).sum(-1)


def decode_boxes(head_outs):
    """-> (boxes [B, A, 4] xyxy in input pixels, scores [B, A, C])."""
    boxes, scores = [], []
    for cls, reg, centers, stride in head_outs:
        cls_r, reg_r = _raw(cls), _raw(reg)
        dist = _dfl_expect(reg_r) * stride  # [B, HW, 4] l, t, r, b
        cx, cy = centers[:, 0][None, :], centers[:, 1][None, :]
        x1 = cx - dist[..., 0]
        y1 = cy - dist[..., 1]
        x2 = cx + dist[..., 2]
        y2 = cy + dist[..., 3]
        boxes.append(jnp.stack([x1, y1, x2, y2], -1))
        scores.append(jax.nn.sigmoid(cls_r))
    return jnp.concatenate(boxes, 1), jnp.concatenate(scores, 1)


def _giou(b1, b2):
    """boxes xyxy [..., 4] -> GIoU [...]."""
    x1 = jnp.maximum(b1[..., 0], b2[..., 0])
    y1 = jnp.maximum(b1[..., 1], b2[..., 1])
    x2 = jnp.minimum(b1[..., 2], b2[..., 2])
    y2 = jnp.minimum(b1[..., 3], b2[..., 3])
    inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
    a1 = jnp.clip(b1[..., 2] - b1[..., 0], 0) * \
        jnp.clip(b1[..., 3] - b1[..., 1], 0)
    a2 = jnp.clip(b2[..., 2] - b2[..., 0], 0) * \
        jnp.clip(b2[..., 3] - b2[..., 1], 0)
    union = a1 + a2 - inter
    iou = inter / jnp.maximum(union, 1e-9)
    cx1 = jnp.minimum(b1[..., 0], b2[..., 0])
    cy1 = jnp.minimum(b1[..., 1], b2[..., 1])
    cx2 = jnp.maximum(b1[..., 2], b2[..., 2])
    cy2 = jnp.maximum(b1[..., 3], b2[..., 3])
    carea = jnp.clip(cx2 - cx1, 0) * jnp.clip(cy2 - cy1, 0)
    return iou - (carea - union) / jnp.maximum(carea, 1e-9)


class PPYOLOE(Layer):
    def __init__(self, num_classes: int = 80, width_mult: float = 0.50,
                 depth_mult: float = 0.33, reg_max: int = 16):
        super().__init__()
        self.backbone = CSPResNet(width_mult=width_mult,
                                  depth_mult=depth_mult)
        self.neck = CustomPAN(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes,
                                reg_max=reg_max)
        self.num_classes = num_classes

    def forward(self, images):
        return self.head(self.neck(self.backbone(images)))

    # ------------------------------------------------------------- loss
    def loss(self, head_outs, gt_boxes, gt_labels, gt_mask):  # noqa: C901
        """Center-based static assignment + BCE cls + GIoU reg loss.

        gt_boxes [B, M, 4] xyxy pixels, gt_labels [B, M] int,
        gt_mask [B, M] (1 = real box). Every anchor whose center falls
        inside a gt box is positive for it (nearest-center tie break) —
        a jit-friendly simplification of the reference's TAL assigner.

        NOTE: computed on raw arrays — train through the jitted
        TrainStep/value_and_grad path (the standard detector loop), not
        eager loss.backward().
        """
        cls_all, reg_all, centers_all, strides_all = [], [], [], []
        for cls, reg, centers, stride in head_outs:
            cls_all.append(_raw(cls))
            reg_all.append(_raw(reg))
            centers_all.append(centers)
            strides_all.append(jnp.full((centers.shape[0],), stride,
                                        jnp.float32))
        cls = jnp.concatenate(cls_all, 1)        # [B, A, C]
        reg = jnp.concatenate(reg_all, 1)        # [B, A, 4, n]
        centers = jnp.concatenate(centers_all, 0)  # [A, 2]
        strides = jnp.concatenate(strides_all, 0)  # [A]

        gt_boxes = _raw(gt_boxes)
        gt_labels = _raw(gt_labels).astype(jnp.int32)
        gt_mask = _raw(gt_mask).astype(jnp.float32)

        cx, cy = centers[:, 0], centers[:, 1]
        inside = ((cx[None, :, None] >= gt_boxes[:, None, :, 0]) &
                  (cx[None, :, None] <= gt_boxes[:, None, :, 2]) &
                  (cy[None, :, None] >= gt_boxes[:, None, :, 1]) &
                  (cy[None, :, None] <= gt_boxes[:, None, :, 3]) &
                  (gt_mask[:, None, :] > 0))      # [B, A, M]
        gcx = (gt_boxes[..., 0] + gt_boxes[..., 2]) / 2
        gcy = (gt_boxes[..., 1] + gt_boxes[..., 3]) / 2
        d2 = (cx[None, :, None] - gcx[:, None, :]) ** 2 + \
            (cy[None, :, None] - gcy[:, None, :]) ** 2
        d2 = jnp.where(inside, d2, jnp.inf)
        assigned = jnp.argmin(d2, -1)             # [B, A]
        pos = jnp.isfinite(jnp.min(d2, -1))       # [B, A]

        tgt_boxes = jax.vmap(lambda gb, a: gb[a])(gt_boxes, assigned)
        tgt_labels = jax.vmap(lambda gl, a: gl[a])(gt_labels, assigned)

        # classification: one-hot at assigned class for positives
        onehot = jax.nn.one_hot(tgt_labels, self.num_classes) * \
            pos[..., None]
        cls_loss = _sigmoid_bce(cls, onehot).mean()

        # regression on positives: decoded boxes vs targets
        dist = _dfl_expect(reg) * strides[None, :, None]
        px1 = cx[None] - dist[..., 0]
        py1 = cy[None] - dist[..., 1]
        px2 = cx[None] + dist[..., 2]
        py2 = cy[None] + dist[..., 3]
        pboxes = jnp.stack([px1, py1, px2, py2], -1)
        giou = _giou(pboxes, tgt_boxes)
        npos = jnp.maximum(pos.sum(), 1.0)
        reg_loss = (jnp.where(pos, 1.0 - giou, 0.0)).sum() / npos
        total = cls_loss + 2.0 * reg_loss
        return Tensor(total)

    # ------------------------------------------------------- inference
    def predict(self, images, score_thresh=0.25, nms_thresh=0.6,
                max_dets=100):
        """Host-side decode + class-aware NMS (eval path)."""
        self.eval()
        outs = self.forward(images)
        boxes, scores = decode_boxes(outs)
        boxes = np.asarray(boxes)
        scores = np.asarray(scores)
        results = []
        for b in range(boxes.shape[0]):
            results.append(_nms_single(boxes[b], scores[b],
                                       score_thresh, nms_thresh,
                                       max_dets))
        return results

    def fuse(self):
        """Re-parameterize all RepVgg blocks for deployment."""
        for _, layer in self.named_sublayers(include_self=True):
            if isinstance(layer, RepVggBlock):
                layer.fuse()
        return self


def _sigmoid_bce(logits, targets):
    return jnp.maximum(logits, 0) - logits * targets + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))


def _nms_single(boxes, scores, score_thresh, nms_thresh, max_dets):
    """numpy greedy class-aware NMS -> dict(boxes, scores, labels)."""
    labels = scores.argmax(-1)
    confid = scores.max(-1)
    keep = confid >= score_thresh
    boxes, confid, labels = boxes[keep], confid[keep], labels[keep]
    order = confid.argsort()[::-1]
    boxes, confid, labels = boxes[order], confid[order], labels[order]

    areas = np.clip(boxes[:, 2] - boxes[:, 0], 0, None) * \
        np.clip(boxes[:, 3] - boxes[:, 1], 0, None)
    suppressed = np.zeros(len(boxes), dtype=bool)
    picked: List[int] = []
    for i in range(len(boxes)):
        if suppressed[i]:
            continue
        picked.append(i)
        if len(picked) >= max_dets:
            break
        rest = ~suppressed
        rest[: i + 1] = False
        idx = np.where(rest & (labels == labels[i]))[0]
        if idx.size == 0:
            continue
        x1 = np.maximum(boxes[i, 0], boxes[idx, 0])
        y1 = np.maximum(boxes[i, 1], boxes[idx, 1])
        x2 = np.minimum(boxes[i, 2], boxes[idx, 2])
        y2 = np.minimum(boxes[i, 3], boxes[idx, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        iou = inter / np.maximum(areas[i] + areas[idx] - inter, 1e-9)
        suppressed[idx[iou > nms_thresh]] = True
    picked_arr = np.asarray(picked, dtype=np.int64)
    return {"boxes": boxes[picked_arr], "scores": confid[picked_arr],
            "labels": labels[picked_arr]}


def ppyoloe_s(num_classes: int = 80, **kw):
    return PPYOLOE(num_classes, width_mult=0.50, depth_mult=0.33, **kw)


def ppyoloe_m(num_classes: int = 80, **kw):
    return PPYOLOE(num_classes, width_mult=0.75, depth_mult=0.67, **kw)
