"""GoogLeNet / InceptionV3 (≈ python/paddle/vision/models/googlenet.py,
inceptionv3.py)."""
from __future__ import annotations

from ..nn.container import Sequential
from ..nn.layer import Layer
from ..nn.layers_common import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D,
                                Conv2D, Dropout, Linear, MaxPool2D, ReLU)
from ..ops.manipulation import concat, flatten


class ConvBN(Layer):
    def __init__(self, c_in, c_out, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(c_out)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class Inception(Layer):
    """GoogLeNet inception-v1 block."""

    def __init__(self, c_in, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = ConvBN(c_in, c1, 1)
        self.b2 = Sequential(ConvBN(c_in, c3r, 1),
                             ConvBN(c3r, c3, 3, padding=1))
        self.b3 = Sequential(ConvBN(c_in, c5r, 1),
                             ConvBN(c5r, c5, 5, padding=2))
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             ConvBN(c_in, pool_proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            ConvBN(3, 64, 7, stride=2, padding=3),
            MaxPool2D(3, stride=2, padding=1),
            ConvBN(64, 64, 1), ConvBN(64, 192, 3, padding=1),
            MaxPool2D(3, stride=2, padding=1))
        self.inc3 = Sequential(
            Inception(192, 64, 96, 128, 16, 32, 32),
            Inception(256, 128, 128, 192, 32, 96, 64),
            MaxPool2D(3, stride=2, padding=1))
        self.inc4 = Sequential(
            Inception(480, 192, 96, 208, 16, 48, 64),
            Inception(512, 160, 112, 224, 24, 64, 64),
            Inception(512, 128, 128, 256, 24, 64, 64),
            Inception(512, 112, 144, 288, 32, 64, 64),
            Inception(528, 256, 160, 320, 32, 128, 128),
            MaxPool2D(3, stride=2, padding=1))
        self.inc5 = Sequential(
            Inception(832, 256, 160, 320, 32, 128, 128),
            Inception(832, 384, 192, 384, 48, 128, 128))
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


# --------------------------------------------------------- inception v3
class InceptionA(Layer):
    def __init__(self, c_in, pool_features):
        super().__init__()
        self.b1 = ConvBN(c_in, 64, 1)
        self.b5 = Sequential(ConvBN(c_in, 48, 1),
                             ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(ConvBN(c_in, 64, 1),
                             ConvBN(64, 96, 3, padding=1),
                             ConvBN(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBN(c_in, pool_features, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class InceptionB(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b3 = ConvBN(c_in, 384, 3, stride=2)
        self.b3d = Sequential(ConvBN(c_in, 64, 1),
                              ConvBN(64, 96, 3, padding=1),
                              ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class InceptionC(Layer):
    def __init__(self, c_in, c7):
        super().__init__()
        self.b1 = ConvBN(c_in, 192, 1)
        self.b7 = Sequential(
            ConvBN(c_in, c7, 1), ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            ConvBN(c_in, c7, 1), ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBN(c_in, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                      axis=1)


class InceptionD(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b3 = Sequential(ConvBN(c_in, 192, 1),
                             ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(
            ConvBN(c_in, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class InceptionE(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b1 = ConvBN(c_in, 320, 1)
        self.b3_stem = ConvBN(c_in, 384, 1)
        self.b3_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = Sequential(ConvBN(c_in, 448, 1),
                                   ConvBN(448, 384, 3, padding=1))
        self.b3d_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBN(c_in, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], axis=1),
                       concat([self.b3d_a(d), self.b3d_b(d)], axis=1),
                       self.bp(x)], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            ConvBN(3, 32, 3, stride=2), ConvBN(32, 32, 3),
            ConvBN(32, 64, 3, padding=1), MaxPool2D(3, stride=2),
            ConvBN(64, 80, 1), ConvBN(80, 192, 3),
            MaxPool2D(3, stride=2))
        self.blocks = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def googlenet(**kw):
    return GoogLeNet(**kw)


def inception_v3(**kw):
    return InceptionV3(**kw)
