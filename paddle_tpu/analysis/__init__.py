"""Static program analysis: jaxpr-level audits of jitted programs.

The reference framework dedicates whole subsystems to catching bad
programs before/as they run (``phi/core/enforce.h``, PAPER.md §1 layer
0). The jax-native equivalent is cheaper and stronger: any program this
framework jits can be TRACED WITHOUT EXECUTING and audited as data.

    from paddle_tpu import analysis
    report = analysis.audit(step_fn, params, opt_state, lr, n, *batch,
                            donate=(0, 1))
    report.raise_on_error()          # tier-1 gate: zero ERROR findings
    assert report.donation_coverage == 1.0

Detector passes (see ``detectors.py``): donation misses, host-callback
syncs, dtype leaks (fp64 / bf16-region upcasts), over-budget baked
constants, per-mesh-axis collective byte accounting (cross-checked
against the runtime ``comm.bytes`` counters via
``cross_check_collectives``), and the HBM planner (``memory.py``):
donation-aware buffer liveness computing peak live bytes per program
(``report.memory``, a :class:`MemoryPlan`), gated by
``audit(hbm_budget=)`` / ``PADDLE_HBM_BUDGET`` and cross-checked
against ``device.max_memory_allocated()`` via ``cross_check_memory``.
The flagship programs expose ready-made entry points:
``TrainStep.audit()``, ``DistributedTrainStep.audit()``,
``GenerationSession.audit()``, ``Predictor.audit_generation()``,
``ServingEngine.audit()``. The ledger (``ledger.py``) freezes the
flagship audits into a committed ``docs/programs.json`` manifest with
a tier-1 drift gate (refresh: ``python -m tools.ledger --update``).

The sibling static layer for *Python* (not traced programs) is the
framework lint: ``python -m tools.lint paddle_tpu tests``.
"""
from .auditor import (AuditError, AuditReport, Finding, Severity,
                      abstractify, audit, cross_check_collectives)
from .detectors import (AuditContext, DETECTORS, register_dequant_site,
                        register_detector)
from .memory import (MemoryPlan, cross_check_memory, parse_bytes,
                     plan_memory, resolve_hbm_budget)

__all__ = [
    "AuditContext", "AuditError", "AuditReport", "DETECTORS", "Finding",
    "MemoryPlan", "Severity", "abstractify", "audit",
    "cross_check_collectives", "cross_check_memory", "parse_bytes",
    "plan_memory", "register_dequant_site", "register_detector",
    "resolve_hbm_budget",
]
