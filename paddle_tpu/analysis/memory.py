"""Static HBM planning: donation-aware buffer liveness over jaxprs.

The reference devotes an entire layer to memory (``AllocatorFacade``,
``memory::Alloc`` — PAPER.md §1 layer 1) and ships memory-optimize
passes in its inference stack; the jax-native equivalent is to answer
*will this program fit?* before a single buffer exists. A traced
program is a straight-line tape of equations over explicitly-shaped
buffers, so peak HBM is a linear scan:

  - **args** are resident from dispatch; a DONATED arg's buffer is
    credited back at its last use (XLA aliases it onto a
    shape/dtype-matching output — the same pairing the donation
    detector models), an undonated arg stays resident to the end.
  - **consts** (top-level and every nested ``ClosedJaxpr``'s) are baked
    into the executable and resident for the whole program.
  - **temporaries** appear at their defining equation and die at their
    last use; at each equation the operands and results coexist (a
    matmul holds A, B and C), so the candidate peak is taken AFTER
    allocation and BEFORE frees — except for the donation pairing
    above, which models XLA's in-place aliasing.
  - **outputs** survive to the end.
  - call-like sub-jaxprs (``pjit``/``remat``/custom-derivative bodies)
    are INLINED with their boundary variables aliased, so a temporary
    three ``pjit`` levels down still lands in the right live set;
    control flow (``scan``/``while``/``cond``) stays opaque but
    contributes its body's isolated internal peak as a transient at
    that equation.

The result is a :class:`MemoryPlan` — peak bytes, the top-K live
buffers at the peak with source provenance, and a per-phase breakdown —
and, when a budget is declared (``audit(..., hbm_budget=)`` or
``PADDLE_HBM_BUDGET``), a ``mem.budget`` ERROR finding that fails the
tier-1 audit gates the way every other detector does. The scan is an
*estimate*: XLA's buffer assignment also reuses dead temporaries it is
free to alias, so the plan upper-bounds the resident set; the
predicted-vs-measured test and ``cross_check_memory`` keep the estimate
honest against ``device.max_memory_allocated()``.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, Severity
from .jaxpr_utils import _sub_jaxprs, aval_bytes, source_of, walk_closed

try:  # jax is mid-migration of these to jax.extend.core
    from jax.core import DropVar, Literal, Var  # noqa: F401
except ImportError:  # pragma: no cover - newer jax
    from jax.extend.core import DropVar, Literal, Var  # noqa: F401

#: call-like primitives whose single body jaxpr executes exactly once
#: with the equation's own operands/results as its boundary — safe to
#: inline for liveness (control flow is NOT in this set: a scan body's
#: buffers are transient per iteration, handled as an isolated extra)
_INLINE_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "remat", "remat2",
    "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
})

_SUFFIXES = {
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_bytes(value) -> int:
    """``16GiB`` / ``16G`` / ``1.5e9`` / ``123456`` -> bytes (binary
    units throughout — HBM capacities are quoted in GiB). Raises
    ValueError on garbage; 0 and negatives are rejected (a budget of
    nothing is a typo, not a constraint)."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        try:
            n = int(value)
        except (ValueError, OverflowError):   # inf / nan literals
            raise ValueError(
                f"unparseable byte size {value!r}") from None
    else:
        text = str(value).strip().lower().replace("_", "")
        mult, num = 1, text
        for suf in sorted(_SUFFIXES, key=len, reverse=True):
            if text.endswith(suf):
                mult, num = _SUFFIXES[suf], text[:-len(suf)].strip()
                break
        try:
            n = int(float(num) * mult)
        except (ValueError, OverflowError):
            # OverflowError: int(float('inf')) and friends — must fold
            # into ValueError or the swallow paths built on it miss it
            raise ValueError(
                f"unparseable byte size {value!r} (want e.g. 16GiB, "
                "512M, or a plain byte count)") from None
    if n <= 0:
        raise ValueError(f"byte size must be positive, got {value!r}")
    return n


def resolve_hbm_budget(explicit=None) -> Optional[int]:
    """The HBM budget in force: an explicit value wins, else
    ``PADDLE_HBM_BUDGET``, else None (no gate). Raises ValueError on a
    garbage explicit value; a garbage ENV value also raises — a budget
    that silently evaporates is worse than no budget."""
    if explicit is not None:
        return parse_bytes(explicit)
    env = os.environ.get("PADDLE_HBM_BUDGET", "").strip()
    if not env or env.lower() in ("0", "off", "none", ""):
        return None
    return parse_bytes(env)


# --------------------------------------------------------------- records

@dataclasses.dataclass
class _Buf:
    """One buffer the scan tracks: an arg leaf, a const, or a value
    produced by an equation."""
    nbytes: int
    kind: str                 # arg | const | temp | out
    label: str
    shape: Tuple
    dtype: str
    source: str = ""
    donated: bool = False


@dataclasses.dataclass
class _Event:
    """One linearized equation: canonical vars it reads/defines plus
    the transient internal peak of any opaque control-flow body."""
    ins: List
    outs: List
    source: str
    prim: str
    extra: int = 0


class MemoryPlan:
    """The planner's answer for one traced program.

    Attributes:
      peak_bytes:   estimated peak live HBM bytes
      peak_source:  ``file.py:line (fn)`` of the equation at the peak
                    ("entry" when the resident args/consts dominate)
      phases:       bytes by phase AT the peak — ``args`` / ``consts`` /
                    ``temps`` / ``outputs`` / ``transient`` (opaque
                    control-flow bodies)
      top:          the top-K live buffers at the peak, largest first:
                    dicts of bytes/kind/shape/dtype/label/source
      args_bytes / consts_bytes / out_bytes: program totals
      arg_bytes:    per-POSITIONAL-audit-arg byte totals (leaf sums in
                    audit() argument order; None when the flattening
                    did not line up)
      donated_bytes: bytes of args credited back by donation
      budget:       the budget the plan was checked against (or None)
    """

    def __init__(self, peak_bytes: int, peak_source: str,
                 phases: Dict[str, int], top: List[dict],
                 args_bytes: int, consts_bytes: int, out_bytes: int,
                 donated_bytes: int, n_eqns: int,
                 arg_bytes: Optional[List[int]] = None):
        self.peak_bytes = int(peak_bytes)
        self.peak_source = peak_source
        self.phases = dict(phases)
        self.top = list(top)
        self.args_bytes = int(args_bytes)
        self.consts_bytes = int(consts_bytes)
        self.out_bytes = int(out_bytes)
        self.donated_bytes = int(donated_bytes)
        self.n_eqns = int(n_eqns)
        self.arg_bytes = arg_bytes
        self.budget: Optional[int] = None

    @property
    def headroom_bytes(self) -> Optional[int]:
        """budget - peak (negative = over budget); None w/o a budget."""
        if self.budget is None:
            return None
        return int(self.budget) - self.peak_bytes

    def summary(self) -> str:
        mib = self.peak_bytes / (1 << 20)
        lines = [f"memory plan: peak {self.peak_bytes} bytes "
                 f"({mib:.1f} MiB) at {self.peak_source or 'entry'}"]
        lines.append("  phases at peak: " + ", ".join(
            f"{k}={v}" for k, v in sorted(self.phases.items())))
        if self.budget is not None:
            lines.append(f"  budget {self.budget} bytes -> headroom "
                         f"{self.headroom_bytes}")
        for t in self.top:
            src = f" [{t['source']}]" if t.get("source") else ""
            lines.append(f"  {t['nbytes']:>12}  {t['kind']:<5} "
                         f"{t['label']}{src}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"MemoryPlan(peak_bytes={self.peak_bytes}, "
                f"n_eqns={self.n_eqns})")


# ---------------------------------------------------------- linearization

def _canon(alias: dict, v):
    while v in alias:
        v = alias[v]
    return v


class _ScopedVar:
    """A per-invocation copy of an inlined sub-jaxpr's Var. JAX caches
    traced ClosedJaxprs, so two call equations of the same jitted
    subfunction share the very same Var OBJECTS — without scoping,
    both invocations' buffers would collapse onto one record and the
    scan would under-count (an optimistic plan is the one failure mode
    a budget gate cannot have)."""
    __slots__ = ("aval",)

    def __init__(self, aval):
        self.aval = aval


def _scoped(scope, v):
    """Translate a raw jaxpr var into the current inlining scope
    (identity at top level)."""
    if scope is None:
        return v
    s = scope.get(v)
    if s is None:
        s = scope[v] = _ScopedVar(v.aval)
    return s


def _buf_of(v, kind: str, label: str, source: str = "",
            donated: bool = False) -> _Buf:
    aval = v.aval
    return _Buf(aval_bytes(aval), kind, label,
                tuple(getattr(aval, "shape", ())),
                str(getattr(aval, "dtype", "")), source, donated)


def _inline_target(eqn):
    """(open_jaxpr, closed_or_None) when the equation is a call whose
    single body runs once with 1:1 boundary vars; None otherwise."""
    if eqn.primitive.name not in _INLINE_PRIMS:
        return None
    for sub, closed in _sub_jaxprs(eqn):
        if len(sub.invars) == len(eqn.invars) and \
                len(sub.outvars) == len(eqn.outvars):
            return sub, closed
    return None


def _linearize(jaxpr, alias: dict, events: List[_Event],
               var_info: Dict[object, _Buf], scope: Optional[dict] = None):
    """Flatten ``jaxpr`` (inlining call-like bodies, aliasing their
    boundary vars onto the caller's) into ``events``; every var that
    can hold bytes gets a ``var_info`` record. Consts are registered
    zero-cost here — the resident const total is accounted ONCE by
    ``walk_closed`` so nothing is double counted across inlining.
    ``scope`` renames this invocation's vars (see :class:`_ScopedVar`):
    each INLINED call site gets a fresh scope, so repeated calls of
    one cached sub-jaxpr keep distinct buffers."""
    for cv in getattr(jaxpr, "constvars", []):
        sv = _scoped(scope, cv)
        if sv not in var_info:
            var_info[sv] = _Buf(0, "const", "const", (), "")
    for eqn in jaxpr.eqns:
        target = _inline_target(eqn)
        if target is not None:
            inner, _closed = target
            inner_scope: dict = {}
            for iv, ov in zip(inner.invars, eqn.invars):
                siv = _scoped(inner_scope, iv)
                if isinstance(ov, Literal):
                    var_info[siv] = _buf_of(iv, "temp", "literal",
                                            source_of(eqn))
                    var_info[siv].nbytes = 0  # inline scalar constant
                else:
                    alias[siv] = _canon(alias, _scoped(scope, ov))
            _linearize(inner, alias, events, var_info, inner_scope)
            for ov, sv in zip(eqn.outvars, inner.outvars):
                sov = _scoped(scope, ov)
                if isinstance(sv, Literal):
                    # constant-valued output: a fresh (tiny) buffer
                    var_info[sov] = _buf_of(
                        ov, "temp", f"{eqn.primitive.name} const out",
                        source_of(eqn))
                    events.append(_Event([], [sov], source_of(eqn),
                                         eqn.primitive.name))
                else:
                    alias[sov] = _canon(alias,
                                        _scoped(inner_scope, sv))
            continue
        extra = 0
        for sub, _closed in _sub_jaxprs(eqn):
            extra = max(extra, _isolated_extra(sub))
        src = source_of(eqn)
        ins, seen = [], set()
        for v in eqn.invars:
            if isinstance(v, Literal):
                continue
            c = _canon(alias, _scoped(scope, v))
            if c not in seen:
                seen.add(c)
                ins.append(c)
        outs = []
        for v in eqn.outvars:
            sv = _scoped(scope, v)
            var_info[sv] = _buf_of(
                v, "temp",
                f"{eqn.primitive.name} "
                f"{tuple(getattr(v.aval, 'shape', ()))} "
                f"{getattr(v.aval, 'dtype', '')}", src)
            outs.append(sv)
        events.append(_Event(ins, outs, src, eqn.primitive.name, extra))


def _isolated_extra(jaxpr) -> int:
    """Internal peak of an opaque control-flow body: its boundary
    (invars/constvars) is counted by the caller's live set, so only
    buffers PRODUCED inside contribute. Recursion handles nesting."""
    alias: dict = {}
    events: List[_Event] = []
    var_info: Dict[object, _Buf] = {}
    for v in list(jaxpr.invars) + list(getattr(jaxpr, "constvars", [])):
        var_info[v] = _Buf(0, "arg", "boundary", (), "")
    _linearize(jaxpr, alias, events, var_info)
    outset = {_canon(alias, v) for v in jaxpr.outvars
              if not isinstance(v, Literal)}
    peak, _idx, _ = _scan_peak(events, var_info, outset,
                               base_bytes=0, live0=())
    return peak


# -------------------------------------------------------------- the scan

def _scan_peak(events: List[_Event], var_info: Dict[object, _Buf],
               outset: set, base_bytes: int, live0,
               stop_at: Optional[int] = None):
    """Linear liveness scan. Returns ``(peak, peak_index, live)`` where
    ``peak_index`` is the event index of the peak (-1 = entry) and
    ``live`` is the live var set at ``stop_at`` (used by the second
    pass to reconstruct the peak's live set)."""
    last_use: Dict[object, int] = {}
    for i, ev in enumerate(events):
        for v in ev.ins:
            last_use[v] = i

    live = set(live0)
    cur = base_bytes + sum(var_info[v].nbytes for v in live)
    peak, peak_idx = cur, -1
    for i, ev in enumerate(events):
        dying_donated = [
            v for v in ev.ins
            if (info := var_info.get(v)) is not None
            and info.kind == "arg" and info.donated
            and last_use.get(v) == i and v not in outset and v in live]
        for v in ev.outs:
            if v in live:       # aliased passthrough: no new buffer
                continue
            info = var_info[v]
            # donation credit: XLA aliases a donated dying operand onto
            # a shape/dtype-matching result — in-place, no double count
            for d in dying_donated:
                dinfo = var_info[d]
                if (dinfo.shape, dinfo.dtype) == (info.shape,
                                                  info.dtype):
                    dying_donated.remove(d)
                    live.discard(d)
                    cur -= dinfo.nbytes
                    break
            live.add(v)
            cur += info.nbytes
        if cur + ev.extra > peak:
            peak, peak_idx = cur + ev.extra, i
        if stop_at is not None and i == stop_at:
            return peak, peak_idx, live
        for v in list(ev.ins) + list(ev.outs):
            if v not in live or v in outset:
                continue
            if last_use.get(v, -1) <= i:
                info = var_info[v]
                if info.kind == "temp" or (info.kind == "arg"
                                           and info.donated):
                    live.discard(v)
                    cur -= info.nbytes
    return peak, peak_idx, live


def plan_closed(closed_jaxpr, donated: List[bool],
                arg_groups: Optional[List[int]] = None,
                top_k: int = 8) -> MemoryPlan:
    """Build the :class:`MemoryPlan` for one traced ``ClosedJaxpr``.
    ``donated`` aligns with the flattened invars (the auditor's mask);
    ``arg_groups`` — leaves per positional audit argument, in order —
    lets the plan report per-argument byte totals."""
    jaxpr = closed_jaxpr.jaxpr
    alias: dict = {}
    events: List[_Event] = []
    var_info: Dict[object, _Buf] = {}

    invars = list(jaxpr.invars)
    args_bytes = donated_bytes = 0
    for i, v in enumerate(invars):
        don = bool(donated[i]) if i < len(donated) else False
        var_info[v] = _buf_of(
            v, "arg",
            f"arg#{i} {tuple(getattr(v.aval, 'shape', ()))} "
            f"{getattr(v.aval, 'dtype', '')}", donated=don)
        args_bytes += var_info[v].nbytes
        if don:
            donated_bytes += var_info[v].nbytes

    # consts: every ClosedJaxpr in the tree owns buffers baked into the
    # executable — resident for the whole program, counted exactly
    # once. Dedup by object identity: jax caches traced sub-jaxprs, so
    # a helper called at N sites is the SAME ClosedJaxpr N times in
    # the walk but its consts are baked once.
    const_recs: List[_Buf] = []
    seen_closed = set()
    for closed in walk_closed(closed_jaxpr):
        if id(closed) in seen_closed:
            continue
        seen_closed.add(id(closed))
        for var in getattr(closed.jaxpr, "constvars", []):
            b = _buf_of(var, "const",
                        f"const {tuple(getattr(var.aval, 'shape', ()))} "
                        f"{getattr(var.aval, 'dtype', '')}")
            if b.nbytes:
                const_recs.append(b)
    consts_bytes = sum(b.nbytes for b in const_recs)

    _linearize(jaxpr, alias, events, var_info)
    outset = {_canon(alias, v) for v in jaxpr.outvars
              if not isinstance(v, Literal)}
    out_bytes = sum(var_info[v].nbytes for v in outset
                    if v in var_info)

    live0 = tuple(v for v in invars if var_info[v].nbytes)
    peak, peak_idx, _ = _scan_peak(events, var_info, outset,
                                   consts_bytes, live0)
    # second pass reconstructs the live set AT the peak (cheaper than
    # snapshotting every monotone improvement during the first pass)
    if peak_idx >= 0:
        _, _, live_at_peak = _scan_peak(events, var_info, outset,
                                        consts_bytes, live0,
                                        stop_at=peak_idx)
        peak_source = events[peak_idx].source
        transient = events[peak_idx].extra
    else:
        live_at_peak = set(live0)
        peak_source = "entry"
        transient = 0

    phases = {"args": 0, "consts": consts_bytes, "temps": 0,
              "outputs": 0, "transient": transient}
    records: List[_Buf] = list(const_recs)
    for v in live_at_peak:
        info = var_info[v]
        kind = "out" if v in outset else info.kind
        phases["args" if kind == "arg" else
               "outputs" if kind == "out" else "temps"] += info.nbytes
        records.append(dataclasses.replace(info, kind=kind))
    top = [
        {"nbytes": b.nbytes, "kind": b.kind, "shape": list(b.shape),
         "dtype": b.dtype, "label": b.label, "source": b.source}
        for b in sorted(records, key=lambda b: -b.nbytes)[:top_k]]

    arg_bytes = None
    if arg_groups is not None and sum(arg_groups) == len(invars):
        arg_bytes, pos = [], 0
        for n in arg_groups:
            arg_bytes.append(sum(var_info[v].nbytes
                                 for v in invars[pos:pos + n]))
            pos += n
    return MemoryPlan(peak, peak_source, phases, top, args_bytes,
                      consts_bytes, out_bytes, donated_bytes,
                      len(events), arg_bytes)


# ------------------------------------------------------------- detector

def detect_memory(ctx) -> List[Finding]:
    """The ``memory`` audit pass: computes the program's
    :class:`MemoryPlan` (landing on ``report.memory``) and, when a
    budget is in force (``audit(hbm_budget=)`` / ``PADDLE_HBM_BUDGET``),
    emits the ``mem.budget`` ERROR the tier-1 gates fail on."""
    findings: List[Finding] = []
    plan = plan_closed(ctx.closed_jaxpr, ctx.donated,
                       arg_groups=ctx.opt("_arg_groups"),
                       top_k=int(ctx.opt("mem_top_k", 8)))
    try:
        budget = resolve_hbm_budget(ctx.opt("hbm_budget"))
    except ValueError as e:
        budget = None
        findings.append(Finding(
            "mem.budget_invalid", Severity.WARNING,
            f"HBM budget unparseable and therefore NOT enforced: {e}"))
    plan.budget = budget
    ctx.options["_memory"] = plan
    if budget is not None and plan.peak_bytes > budget:
        worst = ", ".join(
            f"{t['nbytes']}B {t['kind']} {t['label']}"
            for t in plan.top[:3])
        findings.append(Finding(
            "mem.budget", Severity.ERROR,
            f"predicted peak {plan.peak_bytes} bytes exceeds the HBM "
            f"budget {budget} (over by {plan.peak_bytes - budget}); "
            f"largest live at peak: {worst}",
            source=plan.peak_source if plan.peak_source != "entry"
            else "",
            data={"peak_bytes": plan.peak_bytes,
                  "budget_bytes": budget,
                  "over_bytes": plan.peak_bytes - budget}))
    return findings


# ------------------------------------------------------- standalone API

def plan_memory(fn, *args, donate=(), static_argnums=(),
                hbm_budget=None, name=None) -> MemoryPlan:
    """Trace ``fn`` on abstract inputs and return its
    :class:`MemoryPlan` directly (the full ``analysis.audit`` with only
    the memory pass selected — nothing executes, no buffer exists)."""
    from .auditor import audit
    report = audit(fn, *args, donate=donate,
                   static_argnums=static_argnums, name=name,
                   checks=("memory",), hbm_budget=hbm_budget)
    return report.memory


def cross_check_memory(report, measured_bytes=None, device=None,
                       rtol: float = 0.25):
    """Cross-check the plan against a MEASURED peak — the
    ``cross_check_collectives`` analog for HBM. Pass the
    ``device.max_memory_allocated()`` delta of exactly one execution of
    the audited program (reset the peak, run once, read it); with
    ``measured_bytes=None`` the current device's peak is read directly.
    Appends a WARNING when the measurement EXCEEDS the plan beyond
    ``rtol`` — the plan is designed as an upper bound of the resident
    set, so an underestimate means the program allocates buffers the
    static scan cannot see (host callbacks materializing arrays,
    backend workspace) and the budget gate is optimistic."""
    plan = getattr(report, "memory", None)
    if plan is None or not getattr(report, "memory_checked", False):
        raise ValueError(
            f"audit[{report.name}] ran without the 'memory' detector "
            "(checks= excluded it); its plan is absent, not zero — "
            "re-audit with the memory pass before cross-checking")
    if measured_bytes is None:
        from .. import device as _device
        measured_bytes = _device.max_memory_allocated(device)
    measured_bytes = int(measured_bytes)
    if measured_bytes > plan.peak_bytes * (1.0 + rtol):
        report.findings.append(Finding(
            "mem.underestimate", Severity.WARNING,
            f"measured peak {measured_bytes} bytes exceeds the "
            f"predicted {plan.peak_bytes} by more than {rtol:.0%}: the "
            "plan is missing allocations (the budget gate is "
            "optimistic for this program)",
            data={"measured": measured_bytes,
                  "predicted": plan.peak_bytes}))
    return report
