"""Jaxpr walking utilities shared by the auditor's detector passes.

A traced program is a tree of jaxprs: the top-level ``ClosedJaxpr`` plus
every sub-jaxpr baked into equation params (``pjit``/``closed_call``
bodies, ``scan``/``while`` carries, ``cond`` branches, ``shard_map``
regions, custom-derivative rules). Detectors care about *every* level —
a host callback hidden three ``pjit`` layers down is still a host
callback — so the walkers here recurse uniformly and carry the
axis-size environment (from ``shard_map`` meshes and ``pmap`` params)
that collective accounting needs.
"""
from __future__ import annotations

from typing import Dict, Iterator, Tuple

import numpy as np


def source_of(eqn) -> str:
    """``file.py:line (fn)`` provenance for one equation, via jax's own
    source-info summarizer; degrades to "" on jaxprs that were built
    without source info (e.g. deserialized programs)."""
    try:
        from jax._src import source_info_util
        return str(source_info_util.summarize(eqn.source_info))
    except Exception:
        return ""


def aval_bytes(aval) -> int:
    """On-device bytes of one abstract value (0 for non-array avals,
    e.g. abstract tokens from effectful primitives)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except Exception:
        return 0


def _sub_jaxprs(eqn):
    """(open_jaxpr, consts_or_None) for every sub-jaxpr in an equation's
    params. ClosedJaxpr params contribute their own consts (they are
    separately baked into the program); open Jaxpr params share the
    parent's."""
    for val in eqn.params.values():
        items = val if isinstance(val, (list, tuple)) else (val,)
        for item in items:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                yield item.jaxpr, item
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                yield item, None


def _axis_sizes_of(eqn) -> Dict[str, int]:
    """Named-axis sizes an equation brings into scope: shard_map carries
    a Mesh param; pmap carries (axis_name, axis_size)."""
    sizes: Dict[str, int] = {}
    mesh = eqn.params.get("mesh")
    if mesh is not None and hasattr(mesh, "shape"):
        try:
            sizes.update({str(k): int(v) for k, v in
                          dict(mesh.shape).items()})
        except Exception:
            pass
    name = eqn.params.get("axis_name")
    size = eqn.params.get("axis_size")
    if name is not None and size is not None:
        for n in (name if isinstance(name, (list, tuple)) else (name,)):
            sizes[str(n)] = int(size)
    return sizes


def walk_eqns(closed_jaxpr) -> Iterator[Tuple[object, Dict[str, int], int]]:
    """Yield ``(eqn, axis_sizes, depth)`` for every equation at every
    nesting level. ``axis_sizes`` maps named mesh/pmap axes visible at
    that equation to their sizes (for collective byte accounting)."""

    def _walk(jaxpr, env: Dict[str, int], depth: int):
        for eqn in jaxpr.eqns:
            yield eqn, env, depth
            inner = _axis_sizes_of(eqn)
            sub_env = {**env, **inner} if inner else env
            for sub, _ in _sub_jaxprs(eqn):
                yield from _walk(sub, sub_env, depth + 1)

    yield from _walk(closed_jaxpr.jaxpr, {}, 0)


def walk_closed(closed_jaxpr) -> Iterator[object]:
    """Yield every ClosedJaxpr in the tree (top level first): each one
    owns ``consts`` that get baked into the compiled program."""
    yield closed_jaxpr

    def _walk(jaxpr):
        for eqn in jaxpr.eqns:
            for sub, closed in _sub_jaxprs(eqn):
                if closed is not None:
                    yield closed
                yield from _walk(sub)

    yield from _walk(closed_jaxpr.jaxpr)
