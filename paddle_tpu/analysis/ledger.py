"""Program regression ledger: the flagship audits frozen as data.

Every capacity-relevant property the auditor computes — structural
fingerprint, donation coverage, planned peak HBM bytes, per-axis
collective payloads, finding counts — is deterministic for a fixed
program, so it can be COMMITTED: ``docs/programs.json`` holds one entry
per flagship program (TrainStep, the fleet step on the default mesh,
the generation prefill/decode pair plus the speculative draft/verify
programs, a Predictor bucket, and the ServingEngine trio in its dense,
paged, and paged-int8 variants). A tier-1 drift gate (the
``docs/metrics.md`` precedent) regenerates the manifest in-process and
compares byte-for-byte — a PR that silently drops a donation, bakes a
constant into a program, or grows its peak HBM fails CI with a JSON
diff that names the program and the field, instead of an on-device OOM
three PRs later.

Deliberate changes refresh the manifest::

    python -m tools.ledger --update     # rewrite docs/programs.json
    python -m tools.ledger --check      # exit 1 on drift (CI form)

The ledger is traced on the CPU backend (tier-1's backend) at the
tier-1 virtual device count (8 — the fleet step's default mesh, and
so its fingerprint, depend on it): kernel selection differs on TPU,
so ``tools/ledger`` pins ``JAX_PLATFORMS`` and ``XLA_FLAGS`` before
jax imports. Audits are trace-only — regeneration allocates no device
buffers and takes seconds.
"""
from __future__ import annotations

import json
import os
from typing import Dict

LEDGER_VERSION = 1

#: env knobs that change the flagship programs (or side-effect their
#: construction): regeneration must be hermetic to them — tools/ledger
#: clears these before importing jax, and the tier-1 drift gate
#: monkeypatches them away
SCRUB_ENV = ("PADDLE_HBM_BUDGET", "PADDLE_KV_CACHE_DTYPE",
             "PADDLE_KV_PAGE_SIZE", "PADDLE_TELEMETRY_PORT",
             "PADDLE_TRACE_SAMPLE")


def ledger_path() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "docs", "programs.json")


def entry_for(report) -> Dict:
    """One committed ledger row from one :class:`AuditReport`: only
    deterministic integers/strings, so regeneration on an unchanged
    tree is byte-stable."""
    mem = report.memory
    return {
        "fingerprint": report.fingerprint,
        "donation_coverage": (round(report.donation_coverage, 4)
                              if report.donation_checked else None),
        "peak_bytes": None if mem is None else mem.peak_bytes,
        "args_bytes": None if mem is None else mem.args_bytes,
        "consts_bytes": None if mem is None else mem.consts_bytes,
        "collective_bytes": {k: int(v) for k, v in
                             sorted(report.collectives.items())},
        "findings": {"errors": len(report.errors),
                     "warnings": len(report.warnings)},
    }


def flagship_reports() -> Dict[str, object]:
    """Build and audit every flagship program on the deterministic
    test-tiny configs (trace-only: nothing executes, no buffers).
    Returns ``{ledger_key: AuditReport}``."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import optimizer

    reports: Dict[str, object] = {}

    # ---- TrainStep (the PR-7 flagship gate's exact config)
    from paddle_tpu.models.gpt import gpt
    paddle.seed(0)
    model = gpt("test-tiny")
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    from paddle_tpu.jit.api import TrainStep
    step = TrainStep(model, opt, lambda out, lbl: model.loss(out, lbl))
    ids = np.zeros((2, 16), np.int32)  # avals only: values never enter
    reports["train_step"] = step.audit(
        paddle.to_tensor(ids), paddle.to_tensor(ids.astype(np.int64)))

    # ---- DistributedTrainStep on the default (world) mesh
    from paddle_tpu.distributed import fleet, topology
    prev = topology.get_hybrid_communicate_group()
    try:
        paddle.seed(0)
        fleet.init()
        dmodel = gpt("test-tiny")
        dopt = fleet.distributed_optimizer(optimizer.AdamW(
            learning_rate=1e-3, parameters=dmodel.parameters()))
        dstep = fleet.DistributedTrainStep(
            dmodel, dopt, lambda out, lbl: dmodel.loss(out, lbl))
        reports["fleet_step"] = dstep.audit(
            paddle.to_tensor(ids),
            paddle.to_tensor(ids.astype(np.int64)))
    finally:
        topology.set_hybrid_communicate_group(prev)

    # ---- generation prefill/decode + the speculative program pair
    from paddle_tpu.generation.api import GenerationSession
    sess = GenerationSession(model)
    pre, dec, draft, verify = sess.audit(2, 16, 128,
                                         speculative="ngram")
    reports["generation.prefill"] = pre
    reports["generation.decode"] = dec
    reports["generation.spec_draft"] = draft
    reports["generation.spec_verify"] = verify

    # ---- Predictor AOT bucket (the serving-bucket program family)
    from paddle_tpu.inference import Config, create_predictor
    cfg = Config().from_layer(
        model, input_spec=[paddle.to_tensor(ids)])
    cfg.enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                          max_batch=2, eos_token_id=None)
    bucket = create_predictor(cfg).audit_generation()
    reports["predictor.prefill.16"] = bucket[("prefill", 16)]
    reports["predictor.decode.16"] = bucket[("decode", 16)]

    # ---- ServingEngine program trio: dense, paged, paged-int8 (the
    # quant variant carries the scale-sidecar geometry through every
    # program, so a misattributed sidecar shows up as byte drift here)
    from paddle_tpu.serving import ServingEngine

    def engine_reports(tag, **serving_kw):
        ecfg = (Config()
                .from_layer(model,
                            input_spec=[paddle.to_tensor(ids)])
                .enable_generation(max_new_tokens=8,
                                   prefill_buckets=(16, 32),
                                   max_batch=2, eos_token_id=None)
                .enable_serving(max_queue=8, prefill_chunk_tokens=16,
                                **serving_kw))
        eng = ServingEngine(ecfg, warmup=False)
        rs = eng.audit()
        reports[f"{tag}.prefill.32"] = rs[("prefill", 32)]
        for prog in ("decode", "admit", "free"):
            reports[f"{tag}.{prog}"] = rs[prog]
        # chunked-prefill programs (enabled on every flagship engine so
        # the ledger pins their geometry): the chunk/final pair always,
        # the span install only where a page table exists
        reports[f"{tag}.prefill_chunk.16"] = rs[("chunk", 16)]
        reports[f"{tag}.prefill_chunk_final.16"] = rs[("chunk_final", 16)]
        if ("install_span",) in rs:
            reports[f"{tag}.install_span"] = rs[("install_span",)]

    engine_reports("serve")
    engine_reports("serve_paged", paged=True, kv_page_size=16)
    engine_reports("serve_quant", paged=True, kv_page_size=16,
                   kv_cache_dtype="int8")
    return reports


def build_ledger() -> Dict:
    return {
        "version": LEDGER_VERSION,
        "backend": "cpu",
        "programs": {name: entry_for(rep)
                     for name, rep in flagship_reports().items()},
    }


def render(ledger: Dict = None) -> str:
    """The exact committed byte content of docs/programs.json."""
    return json.dumps(build_ledger() if ledger is None else ledger,
                      indent=2, sort_keys=True) + "\n"


def check(path: str = None, fresh: Dict = None) -> list:
    """Differences between the committed manifest and a fresh
    regeneration, as human-readable strings (empty = green). The
    tier-1 drift gate asserts this is empty. Pass ``fresh`` to diff
    against an already-built ledger (the gate builds once and checks
    both drift and byte stability from it)."""
    path = path or ledger_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            committed = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path} unreadable ({e}); run "
                "`python -m tools.ledger --update`"]
    if fresh is None:
        fresh = build_ledger()
    diffs = []
    if committed.get("version") != fresh["version"]:
        diffs.append(f"version: {committed.get('version')} != "
                     f"{fresh['version']}")
    old_p = committed.get("programs", {})
    new_p = fresh["programs"]
    for name in sorted(set(old_p) | set(new_p)):
        if name not in old_p:
            diffs.append(f"{name}: NEW program (not in the committed "
                         "ledger)")
            continue
        if name not in new_p:
            diffs.append(f"{name}: committed but no longer built")
            continue
        for field in sorted(set(old_p[name]) | set(new_p[name])):
            a, b = old_p[name].get(field), new_p[name].get(field)
            if a != b:
                diffs.append(f"{name}.{field}: committed {a!r} != "
                             f"regenerated {b!r}")
    return diffs


def update(path: str = None) -> str:
    path = path or ledger_path()
    text = render()
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return path
