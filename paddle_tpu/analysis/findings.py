"""Finding/report types for the program auditor.

A ``Finding`` is one detected property violation with a severity and —
whenever the detector had an equation to point at — ``source`` set to
jax's ``file.py:line (fn)`` provenance for the offending operation, so
a CI failure names the line of model/step code to fix, not the auditor.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional


class Severity(enum.IntEnum):
    """ERROR findings fail tier-1 audit gates; WARNING findings are
    budgeted (donation coverage thresholds); INFO is accounting."""
    INFO = 0
    WARNING = 1
    ERROR = 2

    # python >= 3.11 switched IntEnum str/format to the integer form;
    # pin the name so reports and metric tags are stable across versions
    def __str__(self):
        return self.name

    def __format__(self, spec):
        return format(self.name, spec)


@dataclasses.dataclass
class Finding:
    check: str                 # detector id, e.g. "donation.miss"
    severity: Severity
    message: str
    source: str = ""           # "file.py:line (fn)" from eqn.source_info
    data: dict = dataclasses.field(default_factory=dict)

    def __str__(self):
        src = f" [{self.source}]" if self.source else ""
        return f"{self.severity:>7}  {self.check}: {self.message}{src}"

    def __format__(self, spec):
        return format(str(self), spec)


class AuditReport:
    """All findings from one ``audit()`` run plus the accounting the
    tier-1 gates assert on (donation coverage, per-axis collective
    bytes)."""

    def __init__(self, name: str, findings: List[Finding],
                 donation: Optional[dict] = None,
                 collectives: Optional[Dict[str, int]] = None,
                 memory=None):
        self.name = name
        self.findings = list(findings)
        #: {'donated_bytes', 'missed_bytes', 'unused_bytes', 'coverage'}
        self.donation = donation or {
            "donated_bytes": 0, "missed_bytes": 0, "unused_bytes": 0,
            "coverage": 1.0}
        #: static per-mesh-axis collective payload bytes
        self.collectives = dict(collectives or {})
        #: the program's :class:`analysis.memory.MemoryPlan` (peak live
        #: HBM bytes, top live set at the peak, per-phase breakdown) —
        #: None when the memory pass did not run
        self.memory = memory
        #: the audited function's outputs as ShapeDtypeStructs in their
        #: original pytree structure (set by audit(); = eval_shape of
        #: the program, recovered from the same trace) — lets callers
        #: chain audits without re-tracing
        self.out_shape = None
        #: False when audit(checks=...) excluded the collectives pass:
        #: ``collectives == {}`` then means "not analyzed", not "none",
        #: and cross_check_collectives refuses to compare against it
        self.collectives_checked = True
        #: False when the donation pass did not run (excluded via
        #: checks=, or the invar/leaf-count fail-safe skipped it):
        #: donation_coverage then RAISES instead of reading a vacuous
        #: 1.0 through a tier-1 gate
        self.donation_checked = True
        #: False when the memory pass did not run (checks= excluded
        #: it): cross_check_memory refuses such a report
        self.memory_checked = memory is not None
        #: structural program identity (set by audit(): aval + primitive
        #: histogram + donation hash) — the ledger's drift key
        self.fingerprint: Optional[str] = None

    # ------------------------------------------------------------ slicing
    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    def by_check(self, check: str) -> List[Finding]:
        """Findings whose check id equals ``check`` or is nested under
        it (``by_check('dtype')`` matches ``dtype.fp64``)."""
        return [f for f in self.findings
                if f.check == check or f.check.startswith(check + ".")]

    @property
    def donation_coverage(self) -> float:
        """donated / (donated + missed) bytes over inputs whose
        shape/dtype matches an output (1.0 when nothing is donatable).
        Raises when the donation pass did not run — an absent analysis
        must never satisfy a coverage gate as a vacuous 1.0."""
        if not self.donation_checked:
            raise ValueError(
                f"audit[{self.name}] ran without the donation pass "
                "(checks= excluded it, or input flattening did not "
                "line up with the traced invars); its coverage is "
                "unknown, not 1.0 — re-audit with the 'donation' "
                "detector")
        return float(self.donation.get("coverage", 1.0))

    # ------------------------------------------------------------- output
    def raise_on_error(self):
        if self.errors:
            raise AuditError(self)
        return self

    def summary(self) -> str:
        cov = (f"{self.donation_coverage:.2f}" if self.donation_checked
               else "n/a (pass not run)")
        lines = [f"audit[{self.name}]: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s), "
                 f"{len(self.findings)} finding(s); donation coverage "
                 f"{cov}"]
        for f in sorted(self.findings, key=lambda f: -int(f.severity)):
            lines.append(f"  {f}")
        for axis, nbytes in sorted(self.collectives.items()):
            lines.append(f"  collective[{axis}]: {nbytes} bytes/step")
        if self.memory is not None:
            head = (f" (headroom {self.memory.headroom_bytes})"
                    if self.memory.budget is not None else "")
            lines.append(f"  memory: peak {self.memory.peak_bytes} "
                         f"bytes at {self.memory.peak_source}{head}")
        return "\n".join(lines)

    def record(self):
        """Count findings into the runtime monitor
        (``analysis.findings{check=...}``) — audit() calls this when
        the monitor is enabled, so CI dashboards trend lint/audit debt
        alongside the runtime counters."""
        from ..core import monitor
        for f in self.findings:
            monitor.record_analysis_finding(f.check, f.severity.name)
        if self.memory is not None:
            monitor.record_memory_plan(self.name,
                                       self.memory.peak_bytes)
            over = [f for f in self.findings if f.check == "mem.budget"
                    and f.severity == Severity.ERROR]
            if over:
                monitor.record_budget_violation(self.name, len(over))
        return self

    def __str__(self):
        return self.summary()

    def __repr__(self):
        return (f"AuditReport({self.name!r}, errors={len(self.errors)}, "
                f"warnings={len(self.warnings)})")


class AuditError(AssertionError):
    """Raised by AuditReport.raise_on_error(); the message carries the
    full report so a CI failure is self-explaining."""

    def __init__(self, report: AuditReport):
        self.report = report
        super().__init__(report.summary())
