"""``audit()``: trace a program to its jaxpr and run detector passes.

The entry point of the static-analysis layer (ISSUE 7 / reference
enforce.h analog): where the reference spends whole subsystems catching
bad programs *as they run*, a jax program can be traced WITHOUT
executing and audited as data. ``audit(fn, *abstract_args)`` does
exactly that — abstract inputs in, findings with severity and
``file.py:line`` provenance out — so the invariants the perf/serving
PRs established (donated state, no host syncs in hot paths, bf16-pure
compute, no baked weights) hold for every current and future jitted
program, enforced in tier-1.
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence

import jax

from .detectors import DETECTORS, AuditContext
from .findings import AuditError, AuditReport, Finding, Severity  # noqa: F401 (re-export)


def abstractify(tree):
    """Map a pytree of arrays/Tensors/numbers to ShapeDtypeStructs so
    audits never hold (or transfer) real buffers."""
    from ..core.tensor import Tensor

    def _one(x):
        if isinstance(x, Tensor):
            x = x._data
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x  # python scalars etc.: jax abstracts them itself

    return jax.tree_util.tree_map(
        _one, tree, is_leaf=lambda x: isinstance(x, Tensor))


def _norm_argnums(argnums) -> tuple:
    if argnums is None:
        return ()
    if isinstance(argnums, int):
        return (argnums,)
    return tuple(int(i) for i in argnums)


def _allowed(finding: Finding, allow: Sequence[str]) -> bool:
    """allow entries: a check id ("donation.miss", or a prefix like
    "host_sync"), optionally "@source-substring" to scope it to one
    call site ("host_sync@my_file.py")."""
    for entry in allow:
        check, _, where = entry.partition("@")
        if check and not (finding.check == check
                          or finding.check.startswith(check + ".")):
            continue
        if where and where not in (finding.source + " " + finding.message):
            continue
        return True
    return False


def audit(fn, *args, donate=(), static_argnums=(), name: Optional[str] = None,
          checks: Optional[Iterable[str]] = None,
          allow: Sequence[str] = (),
          min_donation_bytes: int = 1024,
          const_budget_bytes: int = 1 << 20,
          bf16_compute: bool = False,
          hbm_budget=None, mem_top_k: int = 8) -> AuditReport:
    """Trace ``fn`` on abstract inputs and run the detector passes.

    args: example inputs — real arrays, Tensors, or
    ``ShapeDtypeStruct``s (everything is abstractified; nothing
    executes and no buffer is allocated). Positional only, so a
    misspelled audit option raises here instead of being silently
    handed to ``fn`` as a traced operand. ``donate`` mirrors jit's
    ``donate_argnums`` — the donation the DEPLOYED program uses (pass
    the TPU intent even when auditing on CPU, where frameworks often
    disable donation). ``static_argnums`` mirrors jit. ``checks``
    selects a subset of detector passes; ``allow`` suppresses findings
    (entries: check id, optionally ``@source-substring``) — suppressed
    findings stay in the report at INFO with ``data['allowed']``.
    ``hbm_budget`` declares the program's peak-HBM budget (bytes, or a
    suffixed string like ``"16GiB"``; default the ``PADDLE_HBM_BUDGET``
    env) — the memory pass then emits a ``mem.budget`` ERROR when the
    planned peak exceeds it, and the plan itself lands on
    ``report.memory`` (``mem_top_k`` sizes its top-live-buffers list).

    Returns an :class:`AuditReport`; call ``.raise_on_error()`` to turn
    ERROR findings into a failing assertion (the tier-1 gate pattern).
    """
    donate = _norm_argnums(donate)
    static = set(_norm_argnums(static_argnums))
    abstract_args = tuple(abstractify(a) if i not in static else a
                          for i, a in enumerate(args))
    # return_shape=True hands back the output avals in the function's
    # own pytree structure (= eval_shape's result) from the SAME trace,
    # so callers chaining audits (prefill -> decode) never re-trace
    # just to recover operand shapes
    closed, out_shape = jax.make_jaxpr(
        fn, static_argnums=sorted(static), return_shape=True)(
        *abstract_args)

    # flatten the dynamic inputs in invar order with the donation mask
    # (and the per-argument leaf grouping the memory plan reports
    # per-operand byte totals through)
    in_avals = list(closed.in_avals)
    donated = []
    arg_groups = []
    for i, a in enumerate(abstract_args):
        if i in static:
            continue
        n = len(jax.tree_util.tree_leaves(a))
        donated.extend([i in donate] * n)
        arg_groups.append(n)
    if len(donated) != len(in_avals):
        # tracing-order mismatch (exotic pytree): fail safe — donation
        # analysis would misattribute buffers, so skip it loudly
        donated = None
        arg_groups = None

    name = name or getattr(fn, "__name__", "program")
    options = {"min_donation_bytes": min_donation_bytes,
               "const_budget_bytes": const_budget_bytes,
               "bf16_compute": bf16_compute,
               "hbm_budget": hbm_budget, "mem_top_k": mem_top_k,
               "_arg_groups": arg_groups}
    ctx = AuditContext(
        closed_jaxpr=closed, name=name, in_avals=in_avals,
        donated=donated if donated is not None else [False] * len(in_avals),
        out_avals=list(closed.out_avals), options=options)

    selected = dict(DETECTORS)
    if checks is not None:
        unknown = set(checks) - set(DETECTORS)
        if unknown:
            raise ValueError(f"unknown detector(s) {sorted(unknown)}; "
                             f"have {sorted(DETECTORS)}")
        selected = {k: DETECTORS[k] for k in checks}
    if donated is None and "donation" in selected:
        del selected["donation"]

    findings = []
    if donated is None:
        findings.append(Finding(
            "donation.skipped", Severity.INFO,
            "input flattening did not line up with the traced invars; "
            "donation analysis skipped"))
    for detector in selected.values():
        findings.extend(detector(ctx))

    for f in findings:
        if f.severity > Severity.INFO and _allowed(f, allow):
            f.severity = Severity.INFO
            f.data["allowed"] = True

    report = AuditReport(
        name, findings, donation=options.get("_donation"),
        collectives=options.get("_collectives"),
        memory=options.get("_memory"))
    report.out_shape = out_shape
    # distinguish "pass ran and found nothing" from "pass never ran":
    # cross_check_collectives refuses an unchecked report instead of
    # reporting a spurious 0-vs-measured mismatch, donation_coverage
    # raises instead of reading a vacuous 1.0, and cross_check_memory
    # refuses a report whose plan was never built
    report.collectives_checked = "_collectives" in options
    report.donation_checked = "_donation" in options
    report.memory_checked = "_memory" in options
    # stable structural identity for the program ledger: operand/result
    # avals + the primitive histogram (at every nesting level) + the
    # donation signature. Source lines deliberately do NOT enter — a
    # comment-only refactor must not churn docs/programs.json.
    import hashlib

    from .jaxpr_utils import walk_eqns
    hist: dict = {}
    for eqn, _, _ in walk_eqns(closed):
        hist[eqn.primitive.name] = hist.get(eqn.primitive.name, 0) + 1
    h = hashlib.blake2b(digest_size=8)
    h.update(repr([str(a) for a in in_avals]).encode())
    h.update(repr([str(a) for a in closed.out_avals]).encode())
    h.update(repr(sorted(hist.items())).encode())
    h.update(repr(donate).encode())
    report.fingerprint = h.hexdigest()
    from ..core import monitor
    if monitor.enabled:
        report.record()
    return report


def cross_check_collectives(report: AuditReport, snapshot=None,
                            rtol: float = 0.0) -> AuditReport:
    """Cross-check the report's static per-axis collective bytes
    against the runtime ``comm.bytes{axis=...}`` counters (PR 2). Pass
    the ``metrics.snapshot()`` of exactly ONE execution of the audited
    program (enable -> run once -> snapshot). Appends a WARNING per
    axis whose measured bytes diverge from the static estimate beyond
    ``rtol`` — a divergence means the program's collectives are not the
    ones the monitor thinks it is issuing (or vice versa)."""
    if not getattr(report, "collectives_checked", True):
        raise ValueError(
            f"audit[{report.name}] ran without the 'collectives' "
            "detector (checks= excluded it), so its static accounting "
            "is absent, not zero; re-audit with the collectives pass "
            "before cross-checking")
    if snapshot is None:
        from ..core import metrics
        snapshot = metrics.snapshot()
    measured = {}
    for key, entry in snapshot.items():
        if not key.startswith("comm.bytes{"):
            continue
        tags = dict(kv.split("=", 1)
                    for kv in key[len("comm.bytes{"):-1].split(",")
                    if "=" in kv)
        ax = tags.get("axis")
        if ax is not None and "op" in tags:
            measured[ax] = measured.get(ax, 0) + int(entry["value"])
    for ax in sorted(set(report.collectives) | set(measured)):
        stat = report.collectives.get(ax, 0)
        meas = measured.get(ax, 0)
        tol = rtol * max(stat, meas)
        if abs(stat - meas) > tol:
            report.findings.append(Finding(
                "collective.mismatch", Severity.WARNING,
                f"axis {ax!r}: static accounting says {stat} bytes/step"
                f", the comm.bytes counters measured {meas}",
                data={"axis": ax, "static": stat, "measured": meas}))
    return report
