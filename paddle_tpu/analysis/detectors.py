"""Detector passes over a traced program's jaxpr.

Each detector is ``fn(ctx) -> List[Finding]`` over an ``AuditContext``
(the closed jaxpr plus flattened input/output avals and the donation
mask). New project-specific detectors register via
``register_detector`` and run in every subsequent ``audit()``.

The built-in passes encode the invariants PRs 2-6 fought for, as
machine-checked rules instead of one bespoke runtime test each:

  donation      inputs whose buffer an output could reuse but that are
                not donated (doubles peak HBM for train state/KV cache)
  host_sync     pure_callback / io_callback (ERROR) and debug_callback
                (WARNING) equations — a hot-path program must never
                round-trip to Python per step
  dtype         fp64 anywhere (ERROR; one stray np scalar flips whole
                subgraphs to f64 under x64), and — opt-in via
                ``bf16_compute=True`` — f32 results computed from bf16
                inputs (weak-type promotion leaks inside a
                declared-bf16 region)
  constants     literal consts baked into the program over a byte
                budget (compile bloat; usually a captured array that
                should have been an argument)
  quant_escape  a quantized (int8/uint8/int4) buffer widened to a
                float dtype OUTSIDE a registered dequant site
                (WARNING): the int8 KV cache and packed int4 weights
                are sanctioned low-bit storage whose ONLY legal exit
                is the fused dequant in the decode kernels /
                precision.materialize — any other wide consumer is
                either missing its scales (silently wrong numerics)
                or re-widening storage the quantization exists to
                keep narrow
  collectives   per-mesh-axis collective payload bytes, statically
                accounted for cross-checking against the runtime
                ``comm.bytes{axis=...}`` counters (PR 2)
  memory        donation-aware buffer liveness (memory.py): peak live
                HBM bytes per program as a MemoryPlan on
                ``report.memory``, and a ``mem.budget`` ERROR when the
                peak exceeds the declared budget
                (``audit(hbm_budget=)`` / ``PADDLE_HBM_BUDGET``)
"""
from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, Severity
from .jaxpr_utils import aval_bytes, source_of, walk_closed, walk_eqns

# primitive name -> severity for host round-trip hazards
_CALLBACK_PRIMS = {
    "pure_callback": Severity.ERROR,
    "io_callback": Severity.ERROR,
    "outside_call": Severity.ERROR,     # legacy host_callback
    "debug_callback": Severity.WARNING,  # jax.debug.print / breakpoint
}

# collective primitives whose payload we account per mesh axis
_COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "psum_scatter", "reduce_scatter", "all_to_all", "pgather",
})

_F64_DTYPES = (np.dtype("float64"), np.dtype("complex128"))


def _np_dtype(dt) -> Optional[np.dtype]:
    """np.dtype(dt), or None for jax extended dtypes (PRNG keys,
    float8 variants numpy can't interpret)."""
    try:
        return np.dtype(dt)
    except TypeError:
        return None


@dataclasses.dataclass
class AuditContext:
    """Everything a detector pass may inspect. ``in_avals``/``donated``
    align 1:1 with the jaxpr's invars (flattened); ``options`` carries
    audit() keyword knobs (const_budget_bytes, min_donation_bytes,
    bf16_compute, ...)."""
    closed_jaxpr: object
    name: str
    in_avals: List[object]
    donated: List[bool]
    out_avals: List[object]
    options: dict

    def opt(self, key, default=None):
        return self.options.get(key, default)


# ------------------------------------------------------------- donation

def _shape_key(aval) -> Optional[Tuple]:
    shape = getattr(aval, "shape", None)
    dtype = _np_dtype(getattr(aval, "dtype", None))
    if shape is None or dtype is None:
        return None
    return (tuple(shape), dtype.str)


def detect_donation(ctx: AuditContext) -> List[Finding]:
    """Inputs whose shape/dtype matches an output but are not donated:
    XLA must then allocate a second buffer for the output, doubling
    peak memory for exactly the big carried-state arrays (params, opt
    state, KV cache) this framework donates everywhere. Tiny inputs
    (< min_donation_bytes, default 1 KiB — lr scalars, step counters,
    eos flags) are never worth donating and are ignored."""
    min_bytes = int(ctx.opt("min_donation_bytes", 1024))
    out_slots = Counter(k for k in (_shape_key(a) for a in ctx.out_avals)
                        if k is not None)
    findings: List[Finding] = []
    donated_bytes = missed_bytes = unused_bytes = 0

    # donated inputs claim their matching output slot first (that is
    # exactly the pairing XLA's donation matcher performs)
    for aval, don in zip(ctx.in_avals, ctx.donated):
        if not don:
            continue
        key = _shape_key(aval)
        b = aval_bytes(aval)
        if key is not None and out_slots.get(key, 0) > 0:
            out_slots[key] -= 1
            donated_bytes += b
        elif b >= min_bytes:
            unused_bytes += b
            findings.append(Finding(
                "donation.unused", Severity.INFO,
                f"donated input {key and key[0]} {key and key[1]} "
                f"({b} bytes) matches no output; the donation is a "
                "no-op (jax warns at dispatch)", data={"bytes": b}))

    for aval, don in zip(ctx.in_avals, ctx.donated):
        if don:
            continue
        key = _shape_key(aval)
        b = aval_bytes(aval)
        if key is None or b < min_bytes:
            continue
        if out_slots.get(key, 0) > 0:
            out_slots[key] -= 1
            missed_bytes += b
            findings.append(Finding(
                "donation.miss", Severity.WARNING,
                f"input {key[0]} {key[1]} ({b} bytes) matches an "
                "output but is not donated: the update allocates a "
                "second copy instead of writing in place",
                data={"bytes": b, "shape": key[0], "dtype": key[1]}))

    total = donated_bytes + missed_bytes
    ctx.options["_donation"] = {
        "donated_bytes": donated_bytes, "missed_bytes": missed_bytes,
        "unused_bytes": unused_bytes,
        "coverage": (donated_bytes / total) if total else 1.0}
    return findings


# ---------------------------------------------------------- host syncs

def detect_host_callbacks(ctx: AuditContext) -> List[Finding]:
    """pure_callback / io_callback / debug_callback equations anywhere
    in the program (any nesting depth): each one is a host round-trip
    serialized into the device program — in a hot-path program that is
    a per-step sync the async pipeline can never hide."""
    findings = []
    for eqn, _, _ in walk_eqns(ctx.closed_jaxpr):
        sev = _CALLBACK_PRIMS.get(eqn.primitive.name)
        if sev is None:
            continue
        findings.append(Finding(
            "host_sync.callback", sev,
            f"{eqn.primitive.name} inside the compiled program "
            "(host round-trip per step)",
            source=source_of(eqn),
            data={"primitive": eqn.primitive.name}))
    return findings


# --------------------------------------------------------- dtype leaks

def detect_dtype_leaks(ctx: AuditContext) -> List[Finding]:
    findings = []
    seen_f64 = set()

    def _flag_f64(aval, source, what):
        dt = _np_dtype(getattr(aval, "dtype", None))
        if dt is None or dt not in _F64_DTYPES:
            return
        key = (source, str(dt), what)
        if key in seen_f64:
            return
        seen_f64.add(key)
        findings.append(Finding(
            "dtype.fp64", Severity.ERROR,
            f"{np.dtype(dt).name} {what} (fp64 is never intended on "
            "TPU: 10-20x slower and usually a stray numpy default)",
            source=source))

    # index each input/const into its message: with source info absent
    # here, the index is both the dedup key and the only handle the
    # maintainer has on WHICH of N operands is f64
    for i, v in enumerate(ctx.closed_jaxpr.jaxpr.invars):
        shape = tuple(getattr(v.aval, "shape", ()))
        _flag_f64(v.aval, "", f"program input #{i} {shape}")
    for i, v in enumerate(ctx.closed_jaxpr.jaxpr.constvars):
        shape = tuple(getattr(v.aval, "shape", ()))
        _flag_f64(v.aval, "", f"baked constant #{i} {shape}")
    for eqn, _, _ in walk_eqns(ctx.closed_jaxpr):
        src = source_of(eqn)
        for v in eqn.outvars:
            _flag_f64(v.aval, src, f"result of {eqn.primitive.name}")

    if ctx.opt("bf16_compute", False):
        # declared-bf16 region: any f32 value computed FROM bf16 inputs
        # is a promotion leak (a f32/weak-f64 scalar or an implicit
        # upcast re-widens the compute the caller declared narrow);
        # pure-f32 islands (loss accumulators fed by f32) don't match.
        for eqn, _, _ in walk_eqns(ctx.closed_jaxpr):
            in_dts = [_np_dtype(v.aval.dtype) for v in eqn.invars
                      if hasattr(v.aval, "dtype")]
            out_dts = [_np_dtype(v.aval.dtype) for v in eqn.outvars
                       if hasattr(v.aval, "dtype")]
            if any(d is not None and d.name == "bfloat16"
                   for d in in_dts) and \
                    any(d is not None and d.name == "float32"
                        for d in out_dts):
                findings.append(Finding(
                    "dtype.bf16_upcast", Severity.WARNING,
                    f"{eqn.primitive.name} widens bfloat16 input(s) to "
                    "float32 inside a declared-bf16 region (weak-type "
                    "promotion leak: check scalar operand dtypes)",
                    source=source_of(eqn)))
    return findings


# ------------------------------------------------------ baked constants

def detect_baked_constants(ctx: AuditContext) -> List[Finding]:
    """Closure-captured arrays baked into the program as literal
    consts. Small consts are normal (masks, eps); anything over the
    budget bloats every compile, is re-hashed on every jit cache probe,
    and usually should have been an argument (params captured by value
    also silently stop receiving optimizer updates)."""
    budget = int(ctx.opt("const_budget_bytes", 1 << 20))
    findings = []
    total = 0
    for closed in walk_closed(ctx.closed_jaxpr):
        consts = getattr(closed, "consts", None) or []
        constvars = getattr(closed.jaxpr, "constvars", [])
        for var, val in zip(constvars, consts):
            b = aval_bytes(var.aval) or int(getattr(val, "nbytes", 0))
            total += b
            if b >= budget:
                key = _shape_key(var.aval)
                findings.append(Finding(
                    "const.baked", Severity.ERROR,
                    f"constant {key and key[0]} {key and key[1]} "
                    f"({b} bytes) baked into the program (budget "
                    f"{budget}); pass it as an argument instead",
                    data={"bytes": b}))
    ctx.options["_const_bytes"] = total
    return findings


# ------------------------------------------------------- quant escapes

# integer storage dtypes the low-bit serving paths use; a float value
# computed FROM one of these is a dequantization
_QUANT_DTYPE_NAMES = frozenset({"int8", "uint8", "int4", "uint4"})

#: source substrings where int8/int4 -> float widening is sanctioned:
#: the decode kernels' fused dequant, the serving-precision
#: materialize, and the quantization package's own dequant helpers.
#: Project code adding a dequant site registers it here.
QUANT_DEQUANT_SITES = {
    "kernels/flash_attention.py", "inference/precision.py",
    "quantization/int8_compute.py", "quantization/fake_quant.py",
    "quantization/ptq.py", "generation/kv_cache.py",
    "generation/paged_cache.py",
}


def register_dequant_site(source_substring: str) -> str:
    """Sanction a source location (file-path substring matched against
    each finding's ``file.py:line`` provenance) as a legal
    quantized-to-wide dequant site; ``dtype.quant_escape`` stops
    firing there. Returns the substring for decorator-ish use."""
    QUANT_DEQUANT_SITES.add(str(source_substring))
    return source_substring


def detect_quant_escape(ctx: AuditContext) -> List[Finding]:
    """A quantized buffer (int8/int4 — the KV cache pools, packed
    weights) consumed into a FLOAT result outside a registered dequant
    site. Integer-world ops (scatter writes into the cache, page
    gathers, nibble shifts, int8 MXU dots accumulating int32) pass
    freely; the moment a quantized value widens to float anywhere but
    the sanctioned sites, the scales are almost certainly missing —
    WARNING, so the audit gate stays meaningful without blocking
    legitimate new dequant sites (register them)."""
    findings = []
    for eqn, _, _ in walk_eqns(ctx.closed_jaxpr):
        quant_in = False
        for v in eqn.invars:
            dt = _np_dtype(getattr(v.aval, "dtype", None))
            if dt is not None and dt.name in _QUANT_DTYPE_NAMES:
                quant_in = True
                break
        if not quant_in:
            continue
        # name-based float check: np.issubdtype(bfloat16, floating) is
        # FALSE (ml_dtypes extension type), and bf16 is exactly the
        # wide dtype TPU serving dequantizes into — the same gap
        # detect_dtype_leaks works around by name
        out_float = False
        for v in eqn.outvars:
            dt = _np_dtype(getattr(v.aval, "dtype", None))
            if dt is not None and (np.issubdtype(dt, np.floating)
                                   or dt.name == "bfloat16"):
                out_float = True
                break
        if not out_float:
            continue
        src = source_of(eqn) or ""
        if any(site in src for site in QUANT_DEQUANT_SITES):
            continue
        findings.append(Finding(
            "dtype.quant_escape", Severity.WARNING,
            f"{eqn.primitive.name} widens a quantized (int8/int4) "
            "buffer to float outside a registered dequant site — the "
            "dequant scales are probably missing; route through the "
            "fused kernel/materialize paths or "
            "analysis.register_dequant_site() the new site",
            source=src or None,
            data={"primitive": eqn.primitive.name}))
    return findings


# ------------------------------------------------- collective accounting

def detect_collectives(ctx: AuditContext) -> List[Finding]:
    """Static per-mesh-axis collective payload accounting: for every
    collective equation, payload = per-shard operand bytes x axis size
    (= the global tensor bytes the runtime ``comm.bytes{axis=...}``
    counters record). The per-axis totals land on
    ``report.collectives`` for budget assertions and for cross-checking
    a measured run (``cross_check_collectives``)."""
    per_axis: Dict[str, int] = {}
    findings = []
    for eqn, axis_sizes, _ in walk_eqns(ctx.closed_jaxpr):
        if eqn.primitive.name not in _COLLECTIVE_PRIMS:
            continue
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if not isinstance(axes, (list, tuple)):
            axes = (axes,)
        shard_bytes = sum(aval_bytes(v.aval) for v in eqn.invars)
        for ax in axes:
            ax = str(ax)
            size = int(axis_sizes.get(ax, 1))
            nbytes = shard_bytes * size
            per_axis[ax] = per_axis.get(ax, 0) + nbytes
            findings.append(Finding(
                "collective.bytes", Severity.INFO,
                f"{eqn.primitive.name} over axis {ax!r}: {nbytes} "
                f"bytes/step ({shard_bytes} per shard x {size})",
                source=source_of(eqn),
                data={"axis": ax, "op": eqn.primitive.name,
                      "bytes": nbytes}))
    ctx.options["_collectives"] = per_axis
    return findings


# -------------------------------------------------------------- registry

# the buffer-liveness pass lives in its own module (memory.py) — it is
# a planner with its own result type (MemoryPlan), not just findings
from .memory import detect_memory  # noqa: E402

DetectorFn = Callable[[AuditContext], List[Finding]]

DETECTORS: Dict[str, DetectorFn] = {
    "donation": detect_donation,
    "host_sync": detect_host_callbacks,
    "dtype": detect_dtype_leaks,
    "constants": detect_baked_constants,
    "quant_escape": detect_quant_escape,
    "collectives": detect_collectives,
    "memory": detect_memory,
}


def register_detector(name: str, fn: DetectorFn):
    """Add a project-specific pass; it runs in every later audit()
    (names must be new — shadowing a built-in is almost certainly an
    accident)."""
    if name in DETECTORS:
        raise ValueError(f"detector {name!r} already registered")
    DETECTORS[name] = fn
    return fn
