"""paddle_tpu.serving — continuous-batching serving engine + fleet router.

Slot-scheduled decode over one shared donated KV cache: requests queue
through a Future-style front-end, prefill at a small fixed set of
prompt shape buckets, and decode at a fixed batch where finished rows
free their slot in place for the next admission — XLA never retraces
under live traffic (``jit.compile{cause=new_shape}`` == 0 at steady
state) and the decode loop never drains.

``FleetRouter`` fronts N replicas with health-scored admission,
per-replica circuit breakers, bounded re-routing, and zero-drop
rolling deploys; ``InProcessFleet`` is its deterministic one-process
test harness.

See docs/architecture.md "Serving engine" and "Fleet serving router".
"""
from .engine import ServingEngine  # noqa: F401
from .request import (QueueFull, Request, RequestFailed,  # noqa: F401
                      RequestParams, RequestStatus)
from .router import (CircuitBreaker, FleetRouter,  # noqa: F401
                     InProcessFleet, RouterRequest)

__all__ = [
    "CircuitBreaker", "FleetRouter", "InProcessFleet", "QueueFull",
    "Request", "RequestFailed", "RequestParams", "RequestStatus",
    "RouterRequest", "ServingEngine",
]
