"""paddle_tpu.serving — continuous-batching serving engine.

Slot-scheduled decode over one shared donated KV cache: requests queue
through a Future-style front-end, prefill at a small fixed set of
prompt shape buckets, and decode at a fixed batch where finished rows
free their slot in place for the next admission — XLA never retraces
under live traffic (``jit.compile{cause=new_shape}`` == 0 at steady
state) and the decode loop never drains.

See docs/architecture.md "Serving engine".
"""
from .engine import ServingEngine  # noqa: F401
from .request import (QueueFull, Request, RequestFailed,  # noqa: F401
                      RequestParams, RequestStatus)

__all__ = [
    "QueueFull", "Request", "RequestFailed", "RequestParams",
    "RequestStatus", "ServingEngine",
]
