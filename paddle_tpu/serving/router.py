"""Fleet serving router: failure-aware admission over N replicas.

The capstone of the fleet observability stack (ROADMAP item 1): every
signal it routes on already exists — the engine's structured health
reasons (``queue_full:no_free_pages`` vs ``no_free_slots`` vs
``shutdown``), the static HBM planner's ``predicted_headroom_bytes``,
the capacity remainder ``free_tokens``, the ``/fleet/healthz`` rollup —
and this module is the front door that consumes them so the fleet keeps
serving when any single replica is cold, wedged, draining, or dead.

Three behaviours, one class:

**Admission on health.** Each ``submit()`` scores every replica from
its ``health()`` document — ``ready × (1 + free_tokens) ×
headroom_fraction / (1 + queue_depth)`` — and places the request on the
best. Draining replicas and replicas whose health probe raises are
skipped; replicas at their queue bound score themselves out through
``ready=False``.

**Survival.** A per-replica circuit breaker counts consecutive
admission/poll failures; at the threshold it trips OPEN for a
full-jittered exponential backoff window (the TCPStore-client retry
idiom: uniform in ``[0, min(cap, base * 2^trips))`` so N routers don't
re-stampede a recovering replica in lockstep), then admits exactly one
half-open probe whose outcome closes or re-opens it. A rejected or
failed placement re-routes (bounded by ``max_reroutes``) to the
next-best replica — admission is idempotent pre-prefill: the doomed
request never touched a KV page — and an explicit deadline is
propagated as the REMAINING budget, so a re-routed request never
exceeds what its submitter asked for.

**Zero-drop rolling deploys.** ``drain_replica()`` flips a replica out
of rotation and drains it: in-flight decodes finish inside the drain
window, queued requests come back REJECTED("shutdown") and are
re-homed onto survivors by the handle's ``result()`` — no caller ever
sees the drain. A relaunched replica built over the same shared
``jit.compile_cache.ExecutableStore`` pre-warms every program off disk
(hits == program count, misses == 0 — zero XLA compiles on rejoin) and
``add_replica()`` puts it back in rotation.

Observability: the ``serve.router.*`` metrics family (admissions per
replica, reroutes by reason, breaker trips/state), the
``serve.router.*`` flight-recorder events, and the telemetry server's
``/router`` endpoint (``TelemetryServer.attach_router``) serving
``describe()`` — the live replica table with breaker states and
scores. Knobs: ``PADDLE_ROUTER_MAX_REROUTES``,
``PADDLE_ROUTER_BREAKER_THRESHOLD``, ``PADDLE_ROUTER_BREAKER_BASE_S``,
``PADDLE_ROUTER_BREAKER_CAP_S`` (constructor kwargs win).

``InProcessFleet`` is the deterministic harness: N engines in ONE
process (the chaos-harness idiom at fleet scale — CPU CI, no second
host), with ``rolling_deploy()`` wiring the drain → relaunch → rejoin
protocol end to end.

See docs/architecture.md "Fleet serving router".
"""
from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core import flight_recorder, monitor
from .request import (QueueFull, RequestFailed, RequestParams,
                      RequestStatus)

__all__ = ["CircuitBreaker", "FleetRouter", "InProcessFleet",
           "RouterRequest"]

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


def _env_num(name: str, default, cast):
    """``PADDLE_ROUTER_*`` env knob with the garbage-must-not-
    reconfigure contract the engine's env knobs follow."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        monitor.record_swallowed(
            "serving.router.env", ValueError(f"{name}={raw!r}"))
        return default


class CircuitBreaker:
    """Per-replica failure gate. Not thread-safe on its own — the
    owning router mutates it under its lock.

    State machine::

        CLOSED ──(threshold consecutive failures)──► OPEN
          ▲                                            │ backoff:
          │ probe success                              │ uniform[0,
          │                                            ▼  min(cap,
        HALF_OPEN ◄──(backoff elapsed; admits ONE probe) base·2^trips))
          │
          └──(probe failure)──► OPEN (trips+1: longer backoff cap)

    ``trips`` counts consecutive OPEN transitions and is the backoff
    exponent; any success resets both it and the failure count.
    ``clock`` is injectable so the state machine is testable without
    sleeping.
    """

    def __init__(self, threshold: int = 3, base_s: float = 0.05,
                 cap_s: float = 2.0, rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.base_s = base_s
        self.cap_s = cap_s
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self.state = BREAKER_CLOSED
        self.failures = 0        # consecutive, while CLOSED
        self.trips = 0           # consecutive OPEN transitions
        self.open_until = 0.0
        self.probe_in_flight = False

    def admissible(self) -> bool:
        """May a request route here right now? An OPEN breaker past
        its backoff deadline transitions to HALF_OPEN; HALF_OPEN
        admits exactly one probe at a time."""
        if self.state == BREAKER_OPEN and self._clock() >= self.open_until:
            self.state = BREAKER_HALF_OPEN
            self.probe_in_flight = False
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_HALF_OPEN:
            return not self.probe_in_flight
        return False

    def begin(self):
        """A request was routed here (call after ``admissible()``):
        in HALF_OPEN it becomes THE probe."""
        if self.state == BREAKER_HALF_OPEN:
            self.probe_in_flight = True

    def record_success(self) -> bool:
        """An admission on this replica succeeded. Returns True when
        this was the half-open probe closing the breaker."""
        closed = self.state == BREAKER_HALF_OPEN
        self.state = BREAKER_CLOSED
        self.failures = 0
        self.trips = 0
        self.probe_in_flight = False
        return closed

    def record_failure(self) -> Optional[float]:
        """An admission/poll failure. Returns the backoff seconds when
        this failure tripped the breaker OPEN (half-open probe failure
        trips immediately; CLOSED trips at the threshold)."""
        if self.state == BREAKER_HALF_OPEN:
            return self._trip()
        if self.state == BREAKER_CLOSED:
            self.failures += 1
            if self.failures >= self.threshold:
                return self._trip()
        return None

    def backoff_bound(self) -> float:
        """The full-jitter upper bound the NEXT trip would draw from
        (exposed for tests and the /router document)."""
        return min(self.cap_s, self.base_s * (2 ** self.trips))

    def _trip(self) -> float:
        backoff = self._rng.uniform(0.0, self.backoff_bound())
        self.trips += 1
        self.failures = 0
        self.state = BREAKER_OPEN
        self.open_until = self._clock() + backoff
        self.probe_in_flight = False
        return backoff


_router_ids = itertools.count()


class RouterRequest:
    """The caller's handle on a routed request: wraps the engine-level
    :class:`Request` currently carrying it, and survives re-homing —
    when a placement is rejected (queue bound, drain) or fails
    (admission error, pre-prefill) the router swaps a fresh engine
    request in underneath and ``result()`` keeps waiting. ``hops``
    records every placement that re-routed, ``replica`` the current
    home."""

    def __init__(self, router: "FleetRouter", prompt, params: RequestParams,
                 deadline: Optional[float]):
        self.rid = next(_router_ids)
        self.prompt = prompt
        self.params = params
        self.deadline = deadline        # absolute monotonic, or None
        self.inner = None               # the current engine Request
        self.replica: Optional[str] = None
        self.hops: List[Tuple[str, str]] = []   # (replica, reason)
        self.reroutes = 0
        self._router = router
        self._failed: Optional[Tuple[RequestStatus, str]] = None

    @property
    def status(self) -> RequestStatus:
        if self._failed is not None:
            return self._failed[0]
        return self.inner.status if self.inner is not None \
            else RequestStatus.QUEUED

    @property
    def detail(self) -> str:
        if self._failed is not None:
            return self._failed[1]
        return self.inner.detail if self.inner is not None else ""

    @property
    def tokens(self):
        return self.inner.tokens if self.inner is not None else None

    @property
    def ttft(self):
        return self.inner.ttft if self.inner is not None else None

    def done(self) -> bool:
        """Terminal AND not re-routable — a rejected inner request the
        router would still re-home does not count as done."""
        if self._failed is not None:
            return True
        return self.inner is not None and self.inner.done() \
            and not self._router._reroutable(self)

    def result(self, timeout: Optional[float] = None):
        """Block until terminal across every re-route; returns the
        generated token ids for COMPLETED, raises
        :class:`RequestFailed` otherwise."""
        return self._router._await(self, timeout)

    def __repr__(self):
        return (f"RouterRequest(rid={self.rid}, replica={self.replica}, "
                f"status={self.status.value}, reroutes={self.reroutes})")


class _Replica:
    __slots__ = ("name", "engine", "breaker", "draining")

    def __init__(self, name, engine, breaker):
        self.name = name
        self.engine = engine
        self.breaker = breaker
        self.draining = False


class FleetRouter:
    """Failure-aware admission over N ``ServingEngine`` replicas (see
    module docstring). ``replicas`` is a ``{name: engine}`` mapping or
    a list (named ``r0..rN-1``); every mutation of the replica table
    and the totals happens under ``_lock`` — submit() callers, the
    ``result()`` re-route path, and the telemetry thread's ``/router``
    scrape all race here (the lock-discipline lint covers both
    attributes)."""

    def __init__(self, replicas, *, max_reroutes: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_base_s: Optional[float] = None,
                 breaker_cap_s: Optional[float] = None,
                 seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_reroutes = int(
            max_reroutes if max_reroutes is not None
            else _env_num("PADDLE_ROUTER_MAX_REROUTES", 2, int))
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else _env_num("PADDLE_ROUTER_BREAKER_THRESHOLD", 3, int))
        self.breaker_base_s = (
            breaker_base_s if breaker_base_s is not None
            else _env_num("PADDLE_ROUTER_BREAKER_BASE_S", 0.05, float))
        self.breaker_cap_s = (
            breaker_cap_s if breaker_cap_s is not None
            else _env_num("PADDLE_ROUTER_BREAKER_CAP_S", 2.0, float))
        self._rng = random.Random(seed)
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas: Dict[str, _Replica] = {}
        self._stats = {"submitted": 0, "admissions": 0, "reroutes": 0,
                       "rehomed": 0, "rejected": 0, "breaker_trips": 0}
        if not isinstance(replicas, dict):
            replicas = {f"r{i}": eng for i, eng in enumerate(replicas)}
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        for name, engine in replicas.items():
            self.add_replica(name, engine)

    # ----------------------------------------------------- replica table
    def add_replica(self, name: str, engine) -> "FleetRouter":
        """Put a replica in rotation (a relaunched one rejoins here:
        built over the shared ExecutableStore its warmup paid zero XLA
        compiles)."""
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already in rotation")
            self._replicas[name] = _Replica(
                name, engine,
                CircuitBreaker(self.breaker_threshold, self.breaker_base_s,
                               self.breaker_cap_s, rng=self._rng,
                               clock=self._clock))
            n = len(self._replicas)
            monitor.record_router_replicas(n)
            monitor.record_router_breaker_state(name, 0)
        if flight_recorder.enabled:
            flight_recorder.record("serve.router.rejoin", replica=name,
                                   replicas=n)
        return self

    def remove_replica(self, name: str):
        """Drop a replica from the table entirely (rolling deploy:
        after its drain, before its relaunch). Returns the engine."""
        with self._lock:
            rec = self._replicas.pop(name)
            monitor.record_router_replicas(len(self._replicas))
        return rec.engine

    def drain_replica(self, name: str):
        """Rolling-deploy step 1: flip ``name`` out of rotation, then
        drain it — in-flight decodes finish inside the engine's drain
        window, queued requests come back REJECTED("shutdown") and are
        re-homed onto survivors the next time their handle is awaited.
        Returns the (drained) engine."""
        with self._lock:
            rec = self._replicas[name]
            rec.draining = True
            engine = rec.engine
        h = {}
        try:
            h = engine.health()
        except Exception as e:
            monitor.record_swallowed("serving.router.health", e)
        if flight_recorder.enabled:
            flight_recorder.record(
                "serve.router.drain", replica=name,
                queued=h.get("queue_depth", -1),
                in_flight=h.get("slots_busy", -1))
        engine.drain()   # outside the lock: it blocks on live decodes
        return engine

    def engines(self) -> Dict[str, object]:
        with self._lock:
            return {name: rec.engine
                    for name, rec in self._replicas.items()}

    def shutdown(self):
        """Drain every replica (all handles terminal, re-homing
        disabled by virtue of nowhere to go) — the fleet-wide stop."""
        for name in list(self.engines()):
            try:
                self.drain_replica(name)
            except KeyError:
                pass   # removed concurrently

    # ---------------------------------------------------------- scoring
    @staticmethod
    def _score(health: dict) -> float:
        """Admission score: ``ready × (1 + free_tokens) ×
        headroom_fraction / (1 + queue_depth +
        prefill_chunks_queued)``. ``free_tokens`` is the engine's
        dtype-adjusted capacity remainder (an int8 pool at equal HBM
        scores ~2× the bf16 one — comparable across precisions); the
        headroom fraction scales by the static HBM plan when a budget
        gates the replica (predicted headroom / budget, clipped to
        [0, 1]); the queue+chunk divisor spreads ties so a burst
        doesn't pile onto one replica before its occupancy moves —
        pending chunked-prefill work counts like queued requests,
        since every outstanding chunk steals a scheduler iteration
        from decode on that replica."""
        if not health.get("ready", False):
            return 0.0
        free_tokens = health.get("free_tokens") or 0
        frac = 1.0
        budget = health.get("hbm_budget")
        headroom = health.get("predicted_headroom_bytes")
        if budget and headroom is not None:
            frac = max(0.0, min(1.0, headroom / budget))
        depth = health.get("queue_depth") or 0
        chunks = health.get("prefill_chunks_queued") or 0
        return (1.0 + free_tokens) * frac / (1.0 + depth + chunks)

    def _candidates(self) -> List[_Replica]:
        """Placement order (callers hold the lock): half-open probes
        first — a recovering replica's single probe must actually
        reach it even while healthy peers outscore it — then ready
        replicas by score descending, insertion order breaking ties
        deterministically. Draining, OPEN, and not-ready replicas are
        skipped; a health() probe that RAISES counts as a poll failure
        on that replica's breaker."""
        probes, scored = [], []
        for idx, rec in enumerate(self._replicas.values()):
            if rec.draining:
                continue
            if not rec.breaker.admissible():
                continue
            try:
                h = rec.engine.health()
            except Exception as e:
                monitor.record_swallowed("serving.router.health", e)
                self._note_failure(rec, "health_error")
                continue
            if h.get("draining"):
                rec.draining = True   # drained behind our back
                continue
            if rec.breaker.state == BREAKER_HALF_OPEN:
                probes.append((idx, rec))
                continue
            s = self._score(h)
            if s <= 0.0:
                continue   # warming or at its queue bound
            scored.append((-s, idx, rec))
        probes.sort()
        scored.sort()
        return [rec for _, rec in probes] + [rec for _, _, rec in scored]

    # ------------------------------------------------ breaker accounting
    def _note_failure(self, rec: _Replica, kind: str):  # lint: lock-discipline-ok (caller holds self._lock)
        """One admission/poll failure on ``rec`` (callers hold the
        lock); trips the breaker at the threshold."""
        backoff = rec.breaker.record_failure()
        monitor.record_router_breaker_state(
            rec.name, _STATE_CODE[rec.breaker.state])
        if backoff is not None:
            self._stats["breaker_trips"] += 1
            monitor.record_router_breaker_trip(rec.name)
            if flight_recorder.enabled:
                flight_recorder.record(
                    "serve.router.breaker_open", replica=rec.name,
                    cause=kind, backoff_s=round(backoff, 4),
                    trips=rec.breaker.trips)

    def _note_success(self, name: Optional[str]):
        """A request admitted (prefilled or completed) on ``name``."""
        if name is None:
            return
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                return
            closed = rec.breaker.record_success()
            monitor.record_router_breaker_state(rec.name, 0)
        if closed and flight_recorder.enabled:
            flight_recorder.record("serve.router.breaker_close",
                                   replica=name)

    # -------------------------------------------------------- admission
    def submit(self, prompt, params: Optional[RequestParams] = None) \
            -> RouterRequest:
        """Route one prompt to the best replica; returns the re-homing
        Future-style handle immediately. Raises :class:`QueueFull`
        (with the aggregated reason and the terminal handle attached)
        when NO replica can admit, and ``ValueError`` for prompts no
        replica's compiled buckets hold — client errors are not
        re-routed."""
        params = params if params is not None else RequestParams()
        deadline = None if params.deadline_s is None \
            else self._clock() + params.deadline_s
        rr = RouterRequest(self, prompt, params, deadline)
        with self._lock:
            self._stats["submitted"] += 1
        if not self._place(rr):
            reason = rr.hops[-1][1] if rr.hops else "no_admissible_replica"
            rr._failed = (RequestStatus.REJECTED, reason)
            with self._lock:
                self._stats["rejected"] += 1
            monitor.record_router_rejected()
            raise QueueFull(
                f"no replica could admit request {rr.rid} "
                f"({len(self._replicas)} in table): {reason}",
                reason=reason, request=rr)
        return rr

    def _params_for(self, rr: RouterRequest) -> RequestParams:
        """Per-placement params: an explicit deadline propagates as the
        REMAINING budget (absolute deadline pinned at first submit), so
        a re-routed request can never exceed what its submitter asked
        for. Without one, each replica applies its own default window."""
        if rr.deadline is None:
            return rr.params
        remaining = max(0.0, rr.deadline - self._clock())
        return RequestParams(max_new_tokens=rr.params.max_new_tokens,
                             deadline_s=remaining)

    def _place(self, rr: RouterRequest, prev: Optional[str] = None,
               reason: Optional[str] = None) -> bool:
        """Try candidates in order until one admits ``rr``; each failed
        candidate past the first attempt burns one of the request's
        bounded re-routes. Returns False when nothing admitted (the
        caller decides whether that surfaces as QueueFull or as the
        prior placement's failure)."""
        with self._lock:
            for rec in self._candidates():
                if rr.deadline is not None \
                        and self._clock() > rr.deadline:
                    return False
                probe = rec.breaker.state == BREAKER_HALF_OPEN
                try:
                    inner = rec.engine.submit(rr.prompt,
                                              self._params_for(rr))
                except QueueFull as e:
                    if not self._burn_reroute(rr, rec.name, e.reason):
                        return False
                    continue
                except (ValueError, TypeError):
                    raise   # client error: identical on every replica
                except RuntimeError as e:
                    if "shut down" in str(e):
                        rec.draining = True   # drained behind our back
                        kind = "shutdown"
                    else:
                        self._note_failure(rec, "submit_error")
                        monitor.record_swallowed("serving.router.submit",
                                                 e)
                        kind = "error"
                    if not self._burn_reroute(rr, rec.name, kind):
                        return False
                    continue
                rec.breaker.begin()
                rr.inner = inner
                src, rr.replica = rr.replica, rec.name
                self._stats["admissions"] += 1
                monitor.record_router_admission(rec.name)
                if flight_recorder.enabled:
                    if probe:
                        flight_recorder.record("serve.router.breaker_probe",
                                               replica=rec.name, rid=rr.rid)
                    if prev is not None:
                        flight_recorder.record(
                            "serve.router.reroute", rid=rr.rid,
                            src=prev, dst=rec.name,
                            reason=reason or "reroute")
                return True
        return False

    def _burn_reroute(self, rr: RouterRequest, name: str,  # lint: lock-discipline-ok (caller holds self._lock)
                      reason: str) -> bool:
        """Account one failed placement attempt; False once the
        request's re-route budget is spent (callers hold the lock)."""
        rr.hops.append((name, reason))
        if rr.reroutes >= self.max_reroutes:
            return False
        rr.reroutes += 1
        self._stats["reroutes"] += 1
        monitor.record_router_reroute(reason)
        return True

    # ---------------------------------------------------------- waiting
    def _reroutable(self, rr: RouterRequest) -> bool:
        """Would the router re-home this handle's current terminal
        state instead of surfacing it? Retryable: rejected at the
        queue bound, rejected by a drain ("shutdown" — the zero-drop
        re-home), or a failed admission that never emitted a token
        (idempotent pre-prefill). Bounded by the re-route budget and
        the original deadline."""
        inner = rr.inner
        if inner is None or not inner.done() or rr._failed is not None:
            return False
        if rr.reroutes >= self.max_reroutes:
            return False
        if rr.deadline is not None and self._clock() > rr.deadline:
            return False
        st, detail = inner.status, inner.detail
        if st is RequestStatus.REJECTED and (
                detail.startswith("queue_full") or detail == "shutdown"):
            return True
        return st is RequestStatus.CANCELLED \
            and detail.startswith("admission error") \
            and inner.n_emitted == 0

    def _failure_reason(self, detail: str) -> str:
        return "admission_error" if detail.startswith("admission error") \
            else detail

    def _await(self, rr: RouterRequest, timeout: Optional[float]):
        """The re-homing wait loop behind ``RouterRequest.result()``."""
        wait_deadline = None if timeout is None \
            else self._clock() + timeout
        while True:
            if rr._failed is not None:
                raise RequestFailed(*rr._failed)
            inner = rr.inner
            remaining = None if wait_deadline is None \
                else max(0.0, wait_deadline - self._clock())
            try:
                tokens = inner.result(timeout=remaining)
            except RequestFailed:
                if not self._handle_failure(rr):
                    raise
                continue
            self._note_success(rr.replica)
            return tokens

    def _handle_failure(self, rr: RouterRequest) -> bool:
        """Classify a terminal failure on the current placement; True
        when the request was re-homed (the await loop continues)."""
        inner = rr.inner
        detail = inner.detail
        if inner.status is RequestStatus.CANCELLED \
                and detail.startswith("admission error"):
            # a failed admission is a replica failure — breaker food —
            # whether or not the request still has re-route budget
            with self._lock:
                rec = self._replicas.get(rr.replica)
                if rec is not None:
                    self._note_failure(rec, "admission_error")
        if not self._reroutable(rr):
            return False
        prev, reason = rr.replica, self._failure_reason(detail)
        with self._lock:
            if not self._burn_reroute(rr, prev, reason):
                return False
            if detail == "shutdown":
                self._stats["rehomed"] += 1
        # _burn_reroute already spent the budget for this attempt;
        # _place itself only burns on its own subsequent rejections
        placed = self._place(rr, prev=prev, reason=reason)
        if not placed:
            with self._lock:
                self._stats["rejected"] += 1
            monitor.record_router_rejected()
        return placed

    # ---------------------------------------------------------- surface
    def describe(self) -> Dict:
        """The ``/router`` telemetry document: routing totals plus the
        live replica table — breaker state/failure counts/backoff
        remaining, drain flag, the health fields scoring reads, and
        the current score."""
        with self._lock:
            now = self._clock()
            replicas = []
            for name, rec in self._replicas.items():
                row = {
                    "name": name,
                    "breaker": rec.breaker.state,
                    "failures": rec.breaker.failures,
                    "trips": rec.breaker.trips,
                    "draining": rec.draining,
                }
                if rec.breaker.state == BREAKER_OPEN:
                    row["open_for_s"] = round(
                        max(0.0, rec.breaker.open_until - now), 4)
                try:
                    h = rec.engine.health()
                    row["health"] = {
                        k: h[k] for k in
                        ("ready", "reason", "queue_depth", "free_slots",
                         "free_tokens", "capacity_tokens",
                         "pending_prefill_tokens",
                         "prefill_chunks_queued",
                         "predicted_headroom_bytes")
                        if k in h}
                    row["score"] = round(self._score(h), 4)
                except Exception as e:
                    monitor.record_swallowed("serving.router.health", e)
                    row["health"] = {"error": type(e).__name__}
                    row["score"] = 0.0
                replicas.append(row)
            return {"replicas": replicas, "max_reroutes": self.max_reroutes,
                    "breaker": {"threshold": self.breaker_threshold,
                                "base_s": self.breaker_base_s,
                                "cap_s": self.breaker_cap_s},
                    **dict(self._stats)}

    @property
    def stats(self) -> Dict:
        with self._lock:
            return dict(self._stats)

    def __repr__(self):
        with self._lock:
            return (f"FleetRouter({len(self._replicas)} replicas, "
                    f"admissions={self._stats['admissions']}, "
                    f"reroutes={self._stats['reroutes']})")


class InProcessFleet:
    """Deterministic N-replica fleet in one process: the chaos-harness
    idiom at fleet scale (CPU CI, no second host). ``engine_factory``
    is called once per replica name — build the engines over ONE shared
    ``jit.compile_cache.ExecutableStore`` inside it so the first
    replica compiles, every sibling AND every relaunch deserializes
    (``rolling_deploy`` rejoins with zero XLA compiles)::

        store = ExecutableStore(root)
        fleet = InProcessFleet(
            lambda name: ServingEngine(cfg, executable_store=store),
            n=3)
        h = fleet.router.submit(prompt)
        fleet.rolling_deploy("r1")      # drain → relaunch → rejoin
        h.result()                      # zero-drop: re-homed if queued
    """

    def __init__(self, engine_factory: Callable[[str], object],
                 n: int = 3, *, names: Optional[List[str]] = None,
                 router_kw: Optional[dict] = None):
        self.factory = engine_factory
        names = list(names) if names is not None \
            else [f"r{i}" for i in range(n)]
        self.router = FleetRouter(
            {name: engine_factory(name) for name in names},
            **(router_kw or {}))

    def __getitem__(self, name: str):
        return self.router.engines()[name]

    def rolling_deploy(self, name: str):
        """One zero-drop rolling-deploy step: drain ``name`` under live
        traffic (the router re-homes its queued work; in-flight decodes
        finish inside the drain window), shut the old engine down,
        relaunch from the factory — pre-warming from the shared
        ExecutableStore — and rejoin. Returns the fresh engine."""
        old = self.router.drain_replica(name)
        self.router.remove_replica(name)
        old.shutdown()
        fresh = self.factory(name)
        self.router.add_replica(name, fresh)
        return fresh

    def shutdown(self):
        self.router.shutdown()
        for engine in self.router.engines().values():
            engine.shutdown()
