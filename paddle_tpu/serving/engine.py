"""Continuous-batching serving engine: slot-scheduled decode over ONE
shared, donated KV cache.

The reference ships serving as a whole layer (paddle/fluid/inference,
~90k LoC — PAPER.md §1); ours is a slot scheduler over the AOT
(prefill, decode) machinery PR 6 built:

- **decode never drains and never retraces.** The decode step always
  runs at the fixed batch of ``max_batch`` slots against the shared
  ring KVCache. A finished row (eos or budget) is masked by its
  ``finished`` lane, its tokens stop advancing, and its ``kv_len`` is
  pinned to 0 in-trace — the slot is freed IN PLACE, no reshape, no
  re-trace, no rebuild of the cache pytree.
- **admission = prefill into a slot.** A queued request is prefilled
  alone (batch 1) at its prompt's shape bucket (the
  ``Config.enable_generation`` bucket set), then a jitted admit program
  copies the row cache into the freed slot (``KVCache.copy_row_from``)
  and resets that slot's token/finished/step/budget lanes. One admit
  program serves every slot — the slot index is data, not shape.
- **paged KV cache + shared-prefix reuse**
  (``enable_serving(paged=True)``): the dense ring is replaced by a
  pool of fixed-size pages addressed through per-slot int32 page
  tables (``generation.PagedKVCache``). Admission plans pages on the
  host (prompt + the request's OWN budget), hashes the prompt's full
  pages against the prefix registry so identical system prompts are
  stored once and reference-counted (copy-on-write at divergence), and
  blocks on FREE PAGES as well as free slots — ``health()`` tells the
  two pressures apart (``no_free_pages`` vs ``no_free_slots``).
  Outputs stay bitwise-equal to the dense cache; page conservation is
  asserted at drain in the chaos tier.
- **every program is compiled at warmup.** ``warmup()`` AOT-lowers one
  prefill executable per bucket plus the decode/admit/free trio; after
  it, a compile the engine is ever forced to do mid-traffic is recorded
  as ``jit.compile{cause=new_shape}`` — the steady-state no-retrace
  invariant the tier-1 gate asserts stays 0. With an executable store
  active (``executable_store=`` or the ``jit.compile_cache`` process
  default) warmup loads serialized executables a previous launch
  persisted — a rolling relaunch warm-starts with zero XLA compiles
  (``jit.compile_cache.hits`` == program count, ``misses`` == 0).
- **precision**: the engine serves the bf16/fp16 cast (and the int8
  weight-only / int8-compute hooks) through the same
  ``inference.precision.serving_params`` the Predictor audits —
  BASELINE.md measured 1.49-1.79x matmul wins at bf16.
- **speculative decoding on the slots**
  (``enable_generation(speculative="ngram")``): the decode step becomes
  a fused prompt-lookup draft + single-dispatch verify — every live
  row advances 1..k+1 tokens per dispatch, with accepted-length-aware
  ``steps``/budget/eos accounting (clamped so a row never writes past
  its budget or ring capacity), per-slot token-history lanes installed
  at admit, and on-device proposed/accepted counters drained into
  ``gen.spec.*`` at each poll. Greedy outputs stay bitwise-equal to
  sequential decode; drain/eviction semantics are unchanged (partial
  results are accepted-only).
- **SLA observability**: the ``serve.*`` metrics family (requests by
  terminal status, queue-depth gauge, TTFT + per-token latency
  histograms, slot occupancy, cancellations) flows through
  ``core.monitor`` into the existing Perfetto export.

Host syncs are confined to the scheduler's poll cadence (every
``poll_every`` decode steps: two [batch]-lane reads), one small sync
per admission (the TTFT measurement point), and one row read per
completion — the decode hot loop itself dispatches without waiting.
"""
from __future__ import annotations

import collections
import heapq
import os
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flight_recorder, monitor
from ..core import slo as slo_mod
from ..core.tensor import Tensor
from ..generation.api import (GenerationConfig, _expect_logits_cache,
                              _round_up, _sample_cfg)
from ..generation.sampling import sample
from .request import (QueueFull, Request, RequestParams, RequestStatus)

__all__ = ["ServingEngine"]


class ServingEngine:
    """Slot-scheduled continuous batching over a live generative layer.

    ::

        cfg = (inference.Config().from_layer(model, input_spec)
               .enable_generation(max_new_tokens=64,
                                  prefill_buckets=(64, 128, 256),
                                  max_batch=8, eos_token_id=50256)
               .enable_serving(max_queue=128))
        engine = ServingEngine(cfg)
        handle = engine.submit(prompt_ids,
                               RequestParams(max_new_tokens=32))
        tokens = handle.result()          # pumps inline if no thread
        # or: engine.serve_forever(request_iter)   # blocking loop
        # or: engine.start(); ...; engine.shutdown()

    The config must name a live layer implementing the KV-cache
    protocol (``Config.from_layer``) and have ``enable_generation()``
    set; ``enable_serving()`` and the keyword arguments below tune the
    scheduler (kwargs win)."""

    def __init__(self, config, *, max_queue: Optional[int] = None,
                 poll_every: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None,
                 default_deadline_s: Optional[float] = None,
                 cache_max_len: Optional[int] = None,
                 warmup: bool = True, seed: Optional[int] = None,
                 executable_store=None,
                 trace_sample: Optional[int] = None,
                 telemetry_port: Optional[int] = None,
                 paged: Optional[bool] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 hbm_budget=None):
        from ..inference.precision import serving_params
        from ..jit.api import _unwrap, functional_call

        layer = getattr(config, "_layer", None)
        if layer is None:
            raise ValueError("ServingEngine needs a live layer: use "
                             "Config.from_layer(...) (artifact-backed "
                             "configs have no cache protocol to drive)")
        opts = getattr(config, "_generation", None)
        if opts is None:
            raise ValueError("ServingEngine reuses the generation "
                             "serving setup: call "
                             "Config.enable_generation() first")
        sopts = getattr(config, "_serving", None) or {}

        def _opt(kw, key, default):
            if kw is not None:
                return kw
            v = sopts.get(key)
            return default if v is None else v

        self.max_queue = int(_opt(max_queue, "max_queue", 64))
        self.poll_every = max(1, int(_opt(poll_every, "poll_every", 4)))
        self.drain_timeout_s = float(  # lint: host-sync-ok (config coercion)
            _opt(drain_timeout_s, "drain_timeout_s", 30.0))
        self.default_deadline_s = _opt(default_deadline_s,
                                       "default_deadline_s", None)
        cache_max_len = _opt(cache_max_len, "cache_max_len", None)
        # per-request tracing: 1-in-N requests carry full queue-wait /
        # prefill / decode-segment spans into the flight recorder (and
        # through it the Perfetto export). Default 8 keeps the span
        # cost off the steady-state p95; 0 turns tracing off.
        env_sample = os.environ.get("PADDLE_TRACE_SAMPLE", "").strip()
        if env_sample.lower() in ("off", "false", "no"):
            env_default = 0
        elif env_sample.isdigit():
            env_default = int(env_sample)
        else:
            if env_sample:  # garbage must not silently re-enable
                monitor.record_swallowed(
                    "serving.trace_sample",
                    ValueError(f"PADDLE_TRACE_SAMPLE={env_sample!r}"))
            env_default = 8
        self.trace_sample = int(_opt(trace_sample, "trace_sample",
                                     env_default))

        # precision: the same serving cast/quant pass the Predictor's
        # run() path audits (int8-compute may swap modules; int4
        # weight-only packs Linear weights two-nibbles-per-byte)
        self._sp = serving_params(layer, config)
        layer = self._sp.layer
        layer.eval()
        self.network = layer
        self.config = config

        # low-bit KV cache (ROADMAP item 4): the serving knob wins over
        # the generation one, PADDLE_KV_CACHE_DTYPE fills the gap. The
        # dtype is baked into every program below (prefill creates the
        # quantized cache in-trace; decode dequantizes in-kernel).
        from ..generation.kv_cache import resolve_cache_dtype
        explicit_cd = sopts.get("kv_cache_dtype")
        if explicit_cd is None:
            explicit_cd = opts.get("kv_cache_dtype")
        self.cache_dtype = resolve_cache_dtype(explicit_cd)
        cache_kw = {} if self.cache_dtype is None \
            else {"cache_dtype": self.cache_dtype}

        self._cfg = GenerationConfig(
            do_sample=opts["do_sample"], temperature=opts["temperature"],
            top_k=opts["top_k"], top_p=opts["top_p"],
            eos_token_id=opts["eos_token_id"],
            pad_token_id=opts["pad_token_id"])
        self.max_new_tokens = int(opts["max_new_tokens"])
        self.max_batch = int(opts["max_batch"])
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")

        # speculative decoding on the slots: the per-poll decode step
        # becomes a fused ngram-draft + single-dispatch verify over the
        # live lanes — each dispatch advances every live row by 1..k+1
        # tokens. Only the model-free self-speculative drafter runs on
        # the engine (a draft model would need its own per-slot cache
        # admission path); generate()/the Predictor serve draft mode.
        from ..generation.speculative import as_spec_config
        self._spec = as_spec_config(opts.get("speculative"),
                                    opts.get("draft_model"))
        if self._spec is not None and self._spec.mode != "ngram":
            raise ValueError(
                "ServingEngine supports speculative='ngram' (the "
                "model-free prompt-lookup drafter); draft-model "
                "speculation is a generate()/Predictor path for now")
        overhang = self._spec.k if self._spec is not None else 0

        max_pos = getattr(getattr(layer, "cfg", None),
                          "max_position_embeddings", None)
        buckets = sorted(
            int(b) for b in opts["prefill_buckets"]
            if max_pos is None
            or b + self.max_new_tokens + overhang <= int(max_pos))
        if not buckets:
            raise ValueError(
                f"no prefill bucket in {opts['prefill_buckets']} fits "
                f"max_position_embeddings={max_pos} with "
                f"max_new_tokens={self.max_new_tokens}"
                + (f" + speculative overhang {overhang}" if overhang
                   else ""))
        self.buckets = buckets
        self.max_len = int(cache_max_len) if cache_max_len else \
            _round_up(buckets[-1] + self.max_new_tokens + overhang)
        if self.max_len < buckets[-1] + self.max_new_tokens + overhang:
            raise ValueError(
                f"cache_max_len {self.max_len} < largest bucket "
                f"{buckets[-1]} + max_new_tokens {self.max_new_tokens}"
                + (f" + speculative verify-window overhang {overhang} "
                   "(the last window's unaccepted draft tokens still "
                   "write their KV before rollback)" if overhang
                   else "")
                + "; the shared ring cache would wrap under a "
                "full-length request")

        # ------------------------------------------------- paged KV cache
        # block-table paged cache + shared-prefix reuse (ROADMAP item 3):
        # K/V live in a pool of fixed-size pages, each slot holds an
        # int32 page table, admission is gated on FREE PAGES (memory)
        # as well as free slots (batch lanes), and identical prompt
        # prefixes reference the same pages copy-on-write.
        self._alloc = None
        self._overhang = overhang
        if bool(_opt(paged, "paged", False)):  # lint: host-sync-ok (config coercion)
            from ..generation.paged_cache import PageAllocator
            env_ps = os.environ.get("PADDLE_KV_PAGE_SIZE", "").strip()
            if env_ps and not env_ps.isdigit():
                # garbage must not silently re-shape the cache (same
                # contract as PADDLE_TRACE_SAMPLE above)
                monitor.record_swallowed(
                    "serving.kv_page_size",
                    ValueError(f"PADDLE_KV_PAGE_SIZE={env_ps!r}"))
            ps = int(_opt(kv_page_size, "kv_page_size",
                          int(env_ps) if env_ps.isdigit() else 128))
            if ps < 1 or self.max_len % ps:
                raise ValueError(
                    f"kv_page_size {ps} must divide the cache length "
                    f"{self.max_len} (PADDLE_KV_PAGE_SIZE / "
                    "enable_serving(kv_page_size=...))")
            self.page_size = ps
            self.pages_per_row = self.max_len // ps
            # default pool: the dense cache's exact HBM footprint
            # (max_batch rows of max_len) plus the reserved null page —
            # the capacity win comes from requests that don't USE
            # max_len and from shared prefixes, not from a bigger pool
            n_pages = int(_opt(kv_pages, "kv_pages",
                               self.max_batch * self.pages_per_row + 1))
            # a pool that cannot cover ONE max-size request would stall
            # the queue head forever with no error — same fail-fast
            # contract as the dense "ring would wrap" check above
            worst = -(-(buckets[-1] + self.max_new_tokens + overhang)
                      // ps)
            if n_pages - 1 < worst:
                raise ValueError(
                    f"kv_pages {n_pages} (1 reserved) cannot hold one "
                    f"full-size request: bucket {buckets[-1]} + "
                    f"max_new_tokens {self.max_new_tokens}"
                    + (f" + speculative overhang {overhang}" if overhang
                       else "")
                    + f" needs {worst} pages of {ps}; raise kv_pages "
                    "or kv_page_size")
            self._alloc = PageAllocator(n_pages, ps)
            self._page_seen: Dict[str, int] = {}
            self._pending_pages: Dict[int, tuple] = {}
            self._row_pages: List[Optional[list]] = [None] * self.max_batch
            self._page_blocked = False
            # (req.id, allocator version) of the last head whose plan
            # failed to commit: while nothing changed in the pool, the
            # pump loop skips re-hashing the prompt and re-walking the
            # registry on every iteration
            self._blocked_key = None

        # ---------------------------------------------- chunked prefill
        # head-of-line fix (ROADMAP item 2a): prompts longer than
        # prefill_chunk_tokens are admitted C tokens at a time, ONE
        # chunk per scheduler iteration, interleaved with the decode
        # dispatch — in-flight streams keep producing tokens while the
        # long prompt fills a persistent batch-1 SIDE cache that the
        # ordinary admit program installs at the final chunk. Opt-in
        # (kwarg > enable_serving > PADDLE_PREFILL_CHUNK_TOKENS); paged
        # engines require page alignment so every completed chunk ends
        # on a page boundary the span-install can commit.
        env_ct = os.environ.get("PADDLE_PREFILL_CHUNK_TOKENS",
                                "").strip()
        if env_ct and not env_ct.isdigit():
            # garbage must not silently enable/resize chunking (same
            # contract as PADDLE_TRACE_SAMPLE / PADDLE_KV_PAGE_SIZE)
            monitor.record_swallowed(
                "serving.prefill_chunk_tokens",
                ValueError(f"PADDLE_PREFILL_CHUNK_TOKENS={env_ct!r}"))
        ct = _opt(prefill_chunk_tokens, "prefill_chunk_tokens",
                  int(env_ct) if env_ct.isdigit() else None)
        self.prefill_chunk_tokens = None
        if ct is not None:
            ct = int(ct)
            if ct < 1:
                raise ValueError(
                    f"prefill_chunk_tokens {ct} must be >= 1 "
                    "(PADDLE_PREFILL_CHUNK_TOKENS / "
                    "enable_serving(prefill_chunk_tokens=...))")
            if self._alloc is not None and ct % self.page_size:
                raise ValueError(
                    f"prefill_chunk_tokens {ct} must be a multiple of "
                    f"kv_page_size {self.page_size}: every completed "
                    "chunk must end on a page boundary so its span "
                    "installs into whole committed pages")
            # the final chunk pads to the chunk width, so the side
            # cache writes up to ceil(bucket/C)*C positions — past
            # max_len the ring modulo would WRAP the write onto the
            # prompt's own prefix (silent corruption, not an error)
            padded_top = -(-buckets[-1] // ct) * ct
            if ct < buckets[-1] and padded_top > self.max_len:
                raise ValueError(
                    f"prefill_chunk_tokens {ct}: the largest bucket "
                    f"{buckets[-1]} pads to {padded_top} chunked "
                    f"tokens, past the cache length {self.max_len} — "
                    "the final padded chunk would wrap the ring onto "
                    "the prompt prefix; raise prefill_chunk_tokens or "
                    "cache_max_len")
            self.prefill_chunk_tokens = ct
        # chunking can only ever trigger for prompts LONGER than one
        # chunk; with every bucket at or under C the programs would be
        # dead weight in warmup
        self._chunk_enabled = (self.prefill_chunk_tokens is not None
                               and self.prefill_chunk_tokens
                               < buckets[-1])
        self._chunking = None   # the (single) in-flight chunked
        #                         admission's scheduler state

        names = self._sp.names
        sp = self._sp
        cfg = self._cfg

        def prefill_fn(state_vals, ids, plen, key, cfg, cache_len):
            params = sp.materialize(state_vals)
            out = functional_call(
                layer, dict(zip(names, params)), Tensor(ids),
                use_cache=True, prompt_len=plen, cache_max_len=cache_len,
                **cache_kw)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits)[:, -1].astype(jnp.float32)
            k0, k1 = jax.random.split(key)
            tok = sample(logits, k0, **_sample_cfg(cfg))
            if cfg.eos_token_id is not None:
                finished = tok == cfg.eos_token_id
            else:
                finished = jnp.zeros(tok.shape, bool)
            return tok, cache, k1, finished

        def step_fn(state_vals, tok, cache, key, finished, steps,
                    budget, out_buf, cfg):
            params = sp.materialize(state_vals)
            out = functional_call(layer, dict(zip(names, params)),
                                  Tensor(tok[:, None]), cache=cache)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits)[:, -1].astype(jnp.float32)
            k0, k1 = jax.random.split(key)
            nxt = sample(logits, k0, **_sample_cfg(cfg))
            rows = jnp.arange(nxt.shape[0], dtype=jnp.int32)
            idx = jnp.clip(steps, 0, out_buf.shape[1] - 1)
            # finished lanes are masked: their buffer entry and step
            # count stay frozen while the fixed-batch step runs on
            out_buf = out_buf.at[rows, idx].set(
                jnp.where(finished, out_buf[rows, idx], nxt))
            steps = steps + jnp.where(finished, 0, 1)
            if cfg.eos_token_id is not None:
                finished = finished | (nxt == cfg.eos_token_id)
            finished = finished | (steps >= budget)
            # dead slots: pin kv_len at 0 so an idle lane neither wraps
            # the ring nor walks the position table out of range while
            # it waits for its next admission
            cache = cache.with_kv_len(
                jnp.where(finished, 0, cache.kv_len))
            return nxt, cache, k1, finished, steps, budget, out_buf

        spec = self._spec

        def spec_step_fn(state_vals, tok, cache, key, finished, steps,
                         budget, out_buf, tok_buf, tok_len, proposed,
                         accepted, cfg, spec):
            from ..generation.speculative import (apply_verify_window,
                                                  ngram_propose)
            params = sp.materialize(state_vals)
            draft = ngram_propose(tok_buf, tok_len, k=spec.k,
                                  n=spec.ngram)
            window = jnp.concatenate([tok[:, None], draft], axis=1)
            out = functional_call(layer, dict(zip(names, params)),
                                  Tensor(window), cache=cache)
            logits, cache = _expect_logits_cache(out)
            logits = _unwrap(logits).astype(jnp.float32)
            k0, k1 = jax.random.split(key)
            # the shared acceptance/clamp/scatter/rollback core —
            # pin_finished_kv is the engine's idle-lane contract (a
            # parked slot must never wrap the ring)
            (tok, cache, finished, steps, out_buf, tok_buf, tok_len,
             proposed, accepted) = apply_verify_window(
                logits, draft, k0, cfg, spec, tok, cache, finished,
                steps, budget, out_buf, tok_buf, tok_len, proposed,
                accepted, pin_finished_kv=True)
            return (tok, cache, k1, finished, steps, budget, out_buf,
                    tok_buf, tok_len, proposed, accepted)

        def admit_lanes(tok, finished, steps, budget, out_buf, slot,
                        first_tok, first_fin, row_budget):
            # the slot's scheduler lanes after admission (shared by the
            # dense and paged admit programs — only the cache install
            # differs); the slot index is a traced scalar, so one
            # program serves every slot
            tok = tok.at[slot].set(first_tok[0])
            steps = steps.at[slot].set(1)
            budget = budget.at[slot].set(row_budget)
            row = jnp.zeros((out_buf.shape[1],), jnp.int32) \
                .at[0].set(first_tok[0])
            out_buf = out_buf.at[slot].set(row)
            finished = finished.at[slot].set(
                first_fin[0] | (row_budget <= 1))
            return tok, finished, steps, budget, out_buf

        def drafter_lanes(tok_buf, tok_len, slot, ids_row, row_plen,
                          first_tok):
            # the drafter's token history: the padded prompt row with
            # the prefill token appended — the n-gram drafter reads
            # prompt AND emitted tokens from one buffer
            row = ids_row.at[row_plen].set(first_tok[0])
            return (tok_buf.at[slot].set(row),
                    tok_len.at[slot].set(row_plen + 1))

        def admit_fn(cache, tok, finished, steps, budget, out_buf,
                     slot, row_cache, first_tok, first_fin, row_budget):
            # install the batch-1 prefill row into the freed slot
            cache = cache.copy_row_from(row_cache, 0, slot)
            (tok, finished, steps, budget, out_buf) = admit_lanes(
                tok, finished, steps, budget, out_buf, slot, first_tok,
                first_fin, row_budget)
            return cache, tok, finished, steps, budget, out_buf

        def spec_admit_fn(cache, tok, finished, steps, budget, out_buf,
                          slot, row_cache, first_tok, first_fin,
                          row_budget, tok_buf, tok_len, ids_row,
                          row_plen):
            (cache, tok, finished, steps, budget, out_buf) = admit_fn(
                cache, tok, finished, steps, budget, out_buf, slot,
                row_cache, first_tok, first_fin, row_budget)
            tok_buf, tok_len = drafter_lanes(tok_buf, tok_len, slot,
                                             ids_row, row_plen,
                                             first_tok)
            return (cache, tok, finished, steps, budget, out_buf,
                    tok_buf, tok_len)

        def free_fn(cache, finished, slot):
            return cache.reset_rows(slot), finished.at[slot].set(True)

        def paged_admit_fn(cache, tok, finished, steps, budget, out_buf,
                           slot, row_cache, first_tok, first_fin,
                           row_budget, table_row, start):
            # paged admission: scatter the batch-1 prefill row into the
            # pool pages named by table_row, SKIPPING the shared-prefix
            # positions below start (they already hold this content —
            # prefill once, reference-count many). slot/table/start are
            # traced data — one program, every slot, every layout.
            cache = cache.install_row(row_cache, slot, table_row, start)
            (tok, finished, steps, budget, out_buf) = admit_lanes(
                tok, finished, steps, budget, out_buf, slot, first_tok,
                first_fin, row_budget)
            return cache, tok, finished, steps, budget, out_buf

        def paged_spec_admit_fn(cache, tok, finished, steps, budget,
                                out_buf, slot, row_cache, first_tok,
                                first_fin, row_budget, table_row, start,
                                tok_buf, tok_len, ids_row, row_plen):
            (cache, tok, finished, steps, budget, out_buf) = \
                paged_admit_fn(cache, tok, finished, steps, budget,
                               out_buf, slot, row_cache, first_tok,
                               first_fin, row_budget, table_row, start)
            tok_buf, tok_len = drafter_lanes(tok_buf, tok_len, slot,
                                             ids_row, row_plen,
                                             first_tok)
            return (cache, tok, finished, steps, budget, out_buf,
                    tok_buf, tok_len)

        def chunk_fn(state_vals, ids, row_cache):
            # one NON-final prefill chunk: decode-mode forward over the
            # persistent batch-1 side cache — attention masks at
            # kv_len + C with queries at offset kv_len (the chunk
            # kernel), the C new KV rows land in the ring, kv_len
            # advances. The logits are never read, so the LM head DCEs
            # out of the compiled program.
            params = sp.materialize(state_vals)
            out = functional_call(layer, dict(zip(names, params)),
                                  Tensor(ids), cache=row_cache)
            _, row_cache = _expect_logits_cache(out)
            return row_cache

        def chunk_final_fn(state_vals, ids, plen, key, row_cache, cfg):
            # the FINAL (pad-to-C) chunk: kv_len clamps to the true
            # prompt length, the hidden state is gathered at the last
            # REAL position, and the first token is sampled — the same
            # (tok, row_cache, key, finished) contract as prefill_fn,
            # so the EXISTING admit program installs the result
            # unchanged.
            params = sp.materialize(state_vals)
            out = functional_call(layer, dict(zip(names, params)),
                                  Tensor(ids), cache=row_cache,
                                  prompt_len=plen)
            logits, row_cache = _expect_logits_cache(out)
            logits = _unwrap(logits)[:, -1].astype(jnp.float32)
            k0, k1 = jax.random.split(key)
            tok = sample(logits, k0, **_sample_cfg(cfg))
            if cfg.eos_token_id is not None:
                finished = tok == cfg.eos_token_id
            else:
                finished = jnp.zeros(tok.shape, bool)
            return tok, row_cache, k1, finished

        def install_span_fn(cache, row_cache, table_row, start):
            # commit one completed chunk's positions into the pool
            # pages the admission planner already committed — table row
            # and kv_len stay untouched, so the slot's lane stays
            # parked (null-page routed) until the final admit installs
            # the pointers atomically
            return cache.install_span(row_cache, table_row, start)

        self._prefill_fn, self._free_fn = prefill_fn, free_fn
        self._chunk_fn = chunk_fn
        self._chunk_final_fn = chunk_final_fn
        self._span_fn = install_span_fn
        self._step_fn = step_fn if spec is None else spec_step_fn
        if self._alloc is None:
            self._admit_fn = admit_fn if spec is None else spec_admit_fn
        else:
            self._admit_fn = paged_admit_fn if spec is None \
                else paged_spec_admit_fn
        # executable persistence: every program warmup() compiles goes
        # through jit.compile_cache (this store, or the process default
        # when None) so a relaunched engine loads instead of recompiling
        self._exe_store = executable_store
        # donate on TPU only (CPU/GPU donation is a no-op that warns
        # once per program); audit() gates the TPU donation INTENT
        tpu = jax.default_backend() == "tpu"
        # the spec admit's drafter tok_buf/tok_len positions — shifted
        # by the paged table_row/start args. ONE definition shared by
        # the jit donation wiring below and audit(): the audited
        # donation set must be the set the production program uses.
        self._spec_admit_buf = (11, 12) if self._alloc is None \
            else (13, 14)
        # the _intent tuples are the TPU donation design regardless of
        # the running backend — audit() and memory_plan() gate against
        # THEM, the jit wiring applies them only where donation works
        if spec is None:
            self._step_donate_intent = (1, 2, 3, 4, 5, 6, 7)
            self._admit_donate_intent = (0, 1, 2, 3, 4, 5, 7)
            step_static = (8,)
        else:
            # the spec step additionally carries the drafter's token
            # buffer/length lanes and the proposed/accepted counters —
            # all donated (in-place across polls, audited as intent).
            # The paged spec admit's tok_buf/tok_len sit two positions
            # later (after table_row/start).
            self._step_donate_intent = tuple(range(1, 12))
            self._admit_donate_intent = (0, 1, 2, 3, 4, 5, 7) \
                + self._spec_admit_buf
            step_static = (12, 13)
        self._free_donate_intent = (0, 1)
        # chunk programs: the side cache is the ONLY donated operand —
        # it round-trips in place every chunk (chunk_fn arg 2,
        # chunk_final_fn arg 4); the span install donates the pool
        # pytree (arg 0) but NOT the source side cache, which the next
        # chunk still reads
        self._chunk_donate_intent = (2,)
        self._chunk_final_donate_intent = (4,)
        self._span_donate_intent = (0,)
        self._step_donate = self._step_donate_intent if tpu else ()
        self._admit_donate = self._admit_donate_intent if tpu else ()
        self._free_donate = self._free_donate_intent if tpu else ()
        self._chunk_donate = self._chunk_donate_intent if tpu else ()
        self._chunk_final_donate = \
            self._chunk_final_donate_intent if tpu else ()
        self._span_donate = self._span_donate_intent if tpu else ()
        self._prefill_jit = jax.jit(prefill_fn, static_argnums=(4, 5))
        self._step_jit = jax.jit(
            self._step_fn, static_argnums=step_static,
            donate_argnums=self._step_donate)
        self._admit_jit = jax.jit(
            self._admit_fn, donate_argnums=self._admit_donate)
        self._free_jit = jax.jit(
            free_fn, donate_argnums=self._free_donate)
        self._chunk_jit = jax.jit(
            chunk_fn, donate_argnums=self._chunk_donate)
        self._chunk_final_jit = jax.jit(
            chunk_final_fn, static_argnums=(5,),
            donate_argnums=self._chunk_final_donate)
        self._span_jit = jax.jit(
            install_span_fn, donate_argnums=self._span_donate)

        # ------------------------------------------------------- state
        self._state = tuple(self._sp.vals)
        if seed is not None:
            self._key = jax.random.PRNGKey(int(seed))
        elif cfg.do_sample:
            from ..core import random as _random
            self._key = _random.next_key()
        else:
            self._key = jax.random.PRNGKey(0)  # greedy: never consumed

        B, cap = self.max_batch, self.max_new_tokens
        sds = jax.ShapeDtypeStruct
        cache_aval = jax.eval_shape(
            lambda s, i, p, k: prefill_fn(s, i, p, k, cfg, self.max_len),
            self._state, sds((B, buckets[0]), jnp.int32),
            sds((B,), jnp.int32), self._key)[1]
        # lane/cache buffers built on HOST and device_put: jnp.zeros
        # would compile one tiny broadcast program per shape — dead
        # weight on the warm-relaunch path the executable store keeps
        # otherwise XLA-free
        quant = getattr(cache_aval, "k_scale", None) is not None
        if self._alloc is None:
            self._cache = jax.tree_util.tree_map(
                lambda a: jax.device_put(np.zeros(a.shape, a.dtype)),
                cache_aval)
        elif quant:
            # paged int8 pool: value pages + their bf16 scale pages
            # (the scales live IN the page, so prefix sharing / COW /
            # reclaim carry them for free) + the saturation counter
            from ..generation.paged_cache import QuantPagedKVCache
            L, _, _, H, D = cache_aval.k.shape
            pool = (L, self._alloc.n_pages, self.page_size, H, D)
            spool = (L, self._alloc.n_pages, self.page_size, H)
            self._cache = QuantPagedKVCache(
                jax.device_put(np.zeros(pool, cache_aval.k.dtype)),
                jax.device_put(np.zeros(pool, cache_aval.v.dtype)),
                jax.device_put(np.zeros((B, self.pages_per_row),
                                        np.int32)),
                jax.device_put(np.zeros((B,), np.int32)),
                jax.device_put(np.zeros(spool, jnp.bfloat16)),
                jax.device_put(np.zeros(spool, jnp.bfloat16)),
                jax.device_put(np.zeros((), np.int32)))
        else:
            # paged pool: layers/heads/head_dim/dtype from the dense
            # prefill aval, rows replaced by the page pool + tables
            from ..generation.paged_cache import PagedKVCache
            L, _, _, H, D = cache_aval.k.shape
            pool = (L, self._alloc.n_pages, self.page_size, H, D)
            self._cache = PagedKVCache(
                jax.device_put(np.zeros(pool, cache_aval.k.dtype)),
                jax.device_put(np.zeros(pool, cache_aval.v.dtype)),
                jax.device_put(np.zeros((B, self.pages_per_row),
                                        np.int32)),
                jax.device_put(np.zeros((B,), np.int32)))
        # the low-bit accounting satellites: the kv_dtype info gauge
        # (what this engine serves — the router reads it beside the
        # capacity numbers) and, when quantized, the HBM bytes the int8
        # storage saved vs the wide dtype (host arithmetic over shapes)
        self._clips_seen = 0
        if quant:
            # the wide dtype the cache WOULD have carried: the serving
            # compute dtype when a precision mode set one, else the
            # model's own float param dtype (a model.bfloat16() under
            # default precision serves a bf16 cache — name check
            # because np.issubdtype(bfloat16, floating) is False)
            wide_dt = self._sp.compute_dtype
            if wide_dt is None:
                wide_dt = next(
                    (v.dtype for v in self._sp.vals
                     if np.issubdtype(np.dtype(v.dtype), np.floating)
                     or np.dtype(v.dtype).name == "bfloat16"),
                    np.float32)
            wide_dt = np.dtype(wide_dt)
            self._kv_dtype_label = "int8"
            saved = 2 * int(np.prod(self._cache.k.shape)) \
                * (wide_dt.itemsize - 1) \
                - 2 * int(np.prod(self._cache.k_scale.shape)) * 2
            monitor.record_kv_quant(bytes_saved=max(0, saved))
        else:
            # the dtype the cache ACTUALLY carries, from its own aval
            self._kv_dtype_label = np.dtype(cache_aval.k.dtype).name
        monitor.record_kv_dtype(self._kv_dtype_label)
        self._tok = jax.device_put(np.zeros((B,), np.int32))
        self._finished = jax.device_put(np.ones((B,), bool))  # empty
        #                                       slots are masked
        self._steps = jax.device_put(np.zeros((B,), np.int32))
        self._budget = jax.device_put(np.zeros((B,), np.int32))
        self._out_buf = jax.device_put(np.zeros((B, cap), np.int32))
        if spec is not None:
            # drafter lanes: per-slot token history (prompt + emitted,
            # the n-gram lookup corpus) and the on-device
            # proposed/accepted counters the poll drains into gen.spec.*
            self._tok_buf = jax.device_put(
                np.zeros((B, self.max_len), np.int32))
            self._tok_len = jax.device_put(np.zeros((B,), np.int32))
            self._proposed = jax.device_put(np.zeros((), np.int32))
            self._accepted = jax.device_put(np.zeros((), np.int32))
            self._spec_seen = (0, 0)   # host mirror for poll deltas

        # chunked prefill's persistent batch-1 SIDE cache: the same
        # dense row cache a bucket prefill would produce (max_len long,
        # quant sidecars included), host-built zeros like the lanes
        # above. Rebuilt from host zeros after every chunked admission
        # or abort — the admit program DONATES it (arg 7), so the
        # buffer is gone either way, and the rebuild is also what
        # resets kv_len to 0 and zeroes the quant clip counter between
        # requests.
        self._row_cache = None
        self._row_cache_aval = None
        if self._chunk_enabled:
            self._row_cache_aval = self._row_avals()[1]
            self._row_cache = self._fresh_row_cache()

        self._slots: List[Optional[Request]] = [None] * B
        self._slot_used = [False] * B          # reuse detection
        self._queue = collections.deque()
        self._qlock = threading.Lock()
        self._pump_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._exes: Dict = {}
        self._warm = False
        self._shutdown = False
        self._steps_since_poll = 0
        self._window_t0: Optional[float] = None
        self._window_steps = 0
        self.stats = dict(submitted=0, admitted=0, completed=0,
                          cancelled=0, rejected=0, slots_reused=0,
                          decode_steps=0, prefills=0, prefill_chunks=0,
                          spec_proposed=0, spec_accepted=0)
        # top-K most expensive terminal requests (heap of
        # (total_s, req id, cost dict)) — the /slo cost table
        self._cost_top: List[tuple] = []
        self._cost_topk = 10
        # goodput ledger (serve.goodput.* family): dispatch windows and
        # admissions charge compute (or compile when a retrace happened
        # inside the window), serve_forever's empty-queue sleeps charge
        # idle, preemption drains charge preemption_recovery; the
        # unattributed residual folds into idle — an un-pumped engine
        # is waiting, not computing. Started after warmup so the
        # one-time compile storm doesn't poison steady-state goodput.
        from ..core import goodput as goodput_mod
        self._goodput = goodput_mod.GoodputLedger(
            "serve", default_bucket="idle")
        # ------------------------------------------------ HBM planning
        # admission control for MEMORY, before a single buffer compiles:
        # with a budget declared (kwarg > enable_serving > env), the
        # static planner (analysis.memory) predicts the engine's peak —
        # weights + kv pool + lanes resident, plus the decode/admission
        # transients — and a config that cannot fit fails HERE, not as
        # an on-device OOM under traffic (the kv_pages-too-small
        # fail-fast contract). health() reports the headroom.
        self._mem_summary = None
        self.hbm_budget = None
        from ..analysis.memory import resolve_hbm_budget
        explicit_budget = _opt(hbm_budget, "hbm_budget", None)
        if explicit_budget is not None:
            # an explicit (kwarg / enable_serving) garbage budget
            # RAISES: the operator asked for a gate and must get one
            self.hbm_budget = resolve_hbm_budget(explicit_budget)
        else:
            try:
                self.hbm_budget = resolve_hbm_budget()
            except ValueError as e:
                # a garbage ENV budget must not crash (or silently
                # gate) the engine: swallow observably, serve ungated
                monitor.record_swallowed("serving.hbm_budget", e)
        if self.hbm_budget is not None:
            mp = self.memory_plan()
            if mp["predicted_peak_bytes"] > self.hbm_budget:
                raise ValueError(
                    f"predicted peak HBM {mp['predicted_peak_bytes']} "
                    f"bytes exceeds hbm_budget {self.hbm_budget} "
                    f"(weights {mp['weights_bytes']}, kv cache "
                    f"{mp['kv_cache_bytes']}, lanes "
                    f"{mp['lanes_bytes']}, decode peak "
                    f"{mp['decode_peak_bytes']}, admission prefill "
                    f"peak {mp['prefill_peak_bytes']}); shrink "
                    "max_batch/cache_max_len/kv_pages or quantize the "
                    "cache (kv_cache_dtype='int8'), or raise the "
                    "budget (PADDLE_HBM_BUDGET / "
                    "enable_serving(hbm_budget=...))")
        # live export surface: opt-in via telemetry_port= (here or in
        # Config.enable_serving) or PADDLE_TELEMETRY_PORT. Started
        # BEFORE warmup so /healthz answers while the replica warms
        # (/readyz stays 503 until warm — a router must not route yet).
        # A bind failure (port still held by a drained-but-not-stopped
        # predecessor) must never crash the engine it would measure:
        # the engine serves un-scraped, the swallow is logged.
        self.telemetry = None
        tp = _opt(telemetry_port, "telemetry_port", None)
        from ..core import telemetry_server
        try:
            if tp is not None:
                self.telemetry = telemetry_server.TelemetryServer(
                    port=int(tp)).start().attach_engine(self)
            else:
                self.telemetry = telemetry_server.start_from_env(self)
        except OSError as e:
            monitor.record_swallowed("serving.telemetry_bind", e)
        # fleet plane opt-in (PADDLE_FLEET_STORE=host:port, exported by
        # the launcher's --fleet_store): publish this replica's metrics
        # + health to the shared TCPStore; on the elected rank the
        # member also aggregates, and the aggregator rides this
        # process's telemetry server at /fleet/*. A bad address or an
        # unreachable store must never take the replica down.
        self.fleet = None
        try:
            from ..distributed import fleet_telemetry
            self.fleet = fleet_telemetry.start_from_env(
                health_fn=self.health)
            if self.fleet is not None and \
                    self.fleet.aggregator is not None and \
                    self.telemetry is not None:
                self.telemetry.attach_aggregator(self.fleet.aggregator)
        except Exception as e:
            monitor.record_swallowed("serving.fleet_start", e)
        if warmup:
            try:
                self.warmup()
            except BaseException:
                # constructor abort: the caller never gets a handle, so
                # shutdown() can never release the port — stop the
                # server here or it leaks (bound, answering "engine
                # gone" forever, blocking the retried engine's bind)
                if self.telemetry is not None:
                    self.telemetry.stop()
                    self.telemetry = None
                if self.fleet is not None:
                    self.fleet.stop()
                    self.fleet = None
                raise
        self._goodput.start()

    # ------------------------------------------------------ compilation
    def _ensure_eval(self):
        # a fit() loop sharing this layer flips it back to train mode
        # every batch; tracing then would bake active dropout into the
        # served program — or close over extra RNG inputs and break the
        # compiled call signature. Same contract as
        # GenerationSession._ensure_eval: force eval at every trace
        # point (executable dispatches are mode-independent).
        if self.network.training:
            self.network.eval()

    def _program_signature(self, cache_key):
        """Structural identity of one scheduler program WITHOUT tracing
        it (the store's traceless manifest key): network code + weights
        structure, the full bucket/shape/sampling/precision config, and
        the engine's own lane avals. None (→ traced path) when the
        network has no deterministic description."""
        from ..jit import compile_cache
        sig = compile_cache.network_signature(self.network)
        if sig is None:
            return None
        sig.update(
            program=("serving",) + tuple(cache_key),
            generation=repr(self._cfg),
            speculative=repr(self._spec),
            buckets=tuple(self.buckets),
            shape=(self.max_batch, self.max_len, self.max_new_tokens),
            paged=(None if self._alloc is None else
                   (self.page_size, self.pages_per_row,
                    self._alloc.n_pages)),
            # the quant geometry: cache dtype + weight packing change
            # every program's operand layout, so they key the manifest
            kv_cache=self.cache_dtype,
            weight_bits=sorted(self._sp.int4) if self._sp.int4 else None,
            precision=(self.config.precision,
                       getattr(self.config, "_int8_compute", False)),
            operands=compile_cache.aval_signature(self._state))
        return sig

    def _compiled(self, cache_key, build, donation=()):
        """One warm program: ``build`` returns the LOWERED module; the
        executable comes from the store on a warm relaunch (manifest
        hit: zero traces, zero XLA compiles) or a fresh ``compile()``
        that is then persisted."""
        exe = self._exes.get(cache_key)
        if exe is None:
            from ..jit import compile_cache
            self._ensure_eval()
            # a compile after warmup means live traffic hit a shape no
            # executable was built for — exactly what the steady-state
            # no-retrace gate (jit.compile{cause=new_shape} == 0) guards
            monitor.record_retrace(
                "first" if not self._warm else "new_shape")
            label = "serving." + ".".join(str(p) for p in cache_key)
            exe = compile_cache.build_or_load(
                self._program_signature(cache_key), build,
                store=self._exe_store,
                extra=dict(kind=label, donation=donation), label=label)
            self._exes[cache_key] = exe
        return exe

    def _exe_prefill(self, bucket: int):
        sds = jax.ShapeDtypeStruct
        return self._compiled(("prefill", bucket),
                              lambda: self._prefill_jit.lower(
            self._state, sds((1, bucket), jnp.int32),
            sds((1,), jnp.int32), sds((2,), jnp.uint32), self._cfg,
            self.max_len))

    def _exe_step(self):
        if self._spec is None:
            return self._compiled(
                ("step",), lambda: self._step_jit.lower(
                    self._state, self._tok, self._cache, self._key,
                    self._finished, self._steps, self._budget,
                    self._out_buf, self._cfg),
                donation=self._step_donate)
        return self._compiled(
            ("spec_step",), lambda: self._step_jit.lower(
                self._state, self._tok, self._cache, self._key,
                self._finished, self._steps, self._budget,
                self._out_buf, self._tok_buf, self._tok_len,
                self._proposed, self._accepted, self._cfg, self._spec),
            donation=self._step_donate)

    def _row_avals(self):
        """(tok, row_cache, finished) avals of a batch-1 prefill — the
        admit program's source operands (bucket-independent: every
        bucket prefills into a cache of the shared max_len)."""
        tok_a, row_cache_a, _, fin_a = jax.eval_shape(
            lambda s, i, p, k: self._prefill_fn(s, i, p, k, self._cfg,
                                                self.max_len),
            self._state,
            jax.ShapeDtypeStruct((1, self.buckets[0]), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.uint32))
        return tok_a, row_cache_a, fin_a

    def _exe_admit(self):
        def build():
            tok_a, row_cache_a, fin_a = self._row_avals()
            scalar = jnp.asarray(0, jnp.int32)
            paged = () if self._alloc is None else (
                jax.ShapeDtypeStruct((self.pages_per_row,), jnp.int32),
                scalar)
            if self._spec is None:
                return self._admit_jit.lower(
                    self._cache, self._tok, self._finished, self._steps,
                    self._budget, self._out_buf, scalar, row_cache_a,
                    tok_a, fin_a, scalar, *paged)
            ids_row = jax.ShapeDtypeStruct((self.max_len,), jnp.int32)
            return self._admit_jit.lower(
                self._cache, self._tok, self._finished, self._steps,
                self._budget, self._out_buf, scalar, row_cache_a,
                tok_a, fin_a, scalar, *paged, self._tok_buf,
                self._tok_len, ids_row, scalar)
        return self._compiled(("admit",), build,
                              donation=self._admit_donate)

    def _exe_free(self):
        return self._compiled(("free",), lambda: self._free_jit.lower(
            self._cache, self._finished,
            jnp.asarray(0, jnp.int32)), donation=self._free_donate)

    def _fresh_row_cache(self):
        """A zeroed chunk side cache (host-built + device_put, same
        XLA-free contract as the lane buffers): kv_len 0, quant clips
        0 — the state every chunked admission must start from."""
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(np.zeros(a.shape, a.dtype)),
            self._row_cache_aval)

    def _exe_chunk(self):
        sds = jax.ShapeDtypeStruct
        C = self.prefill_chunk_tokens
        return self._compiled(
            ("chunk", C), lambda: self._chunk_jit.lower(
                self._state, sds((1, C), jnp.int32),
                self._row_cache_aval),
            donation=self._chunk_donate)

    def _exe_chunk_final(self):
        sds = jax.ShapeDtypeStruct
        C = self.prefill_chunk_tokens
        return self._compiled(
            ("chunk_final", C), lambda: self._chunk_final_jit.lower(
                self._state, sds((1, C), jnp.int32),
                sds((1,), jnp.int32), sds((2,), jnp.uint32),
                self._row_cache_aval, self._cfg),
            donation=self._chunk_final_donate)

    def _exe_span(self):
        sds = jax.ShapeDtypeStruct
        return self._compiled(
            ("install_span",), lambda: self._span_jit.lower(
                self._cache, self._row_cache_aval,
                sds((self.pages_per_row,), jnp.int32),
                jnp.asarray(0, jnp.int32)),
            donation=self._span_donate)

    def warmup(self):
        """Compile every program the scheduler can dispatch (one
        prefill per bucket + the decode/admit/free trio, plus the
        chunk-prefill pair — and the paged span install — when chunked
        prefill is enabled). After this, live traffic only ever hits
        warm executables; any later compile is recorded as
        ``jit.compile{cause=new_shape}``."""
        for b in self.buckets:
            self._exe_prefill(b)
        self._exe_step()
        self._exe_admit()
        self._exe_free()
        if self._chunk_enabled:
            self._exe_chunk()
            self._exe_chunk_final()
            if self._alloc is not None:
                self._exe_span()
        self._warm = True
        return self

    # -------------------------------------------------------- admission
    def submit(self, prompt, params: Optional[RequestParams] = None) \
            -> Request:
        """Enqueue one prompt; returns the Future-style handle
        immediately. Raises :class:`QueueFull` at the queue-depth bound
        and ``ValueError`` for prompts no compiled bucket can hold —
        admission control happens here, not deep in the scheduler."""
        if isinstance(prompt, Tensor):
            prompt = prompt._data
        ids = np.asarray(prompt).reshape(-1).astype(np.int32)  # lint: host-sync-ok (pre-dispatch input prep)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.size > self.buckets[-1]:
            raise ValueError(
                f"prompt of {ids.size} tokens exceeds the largest "
                f"compiled prefill bucket {self.buckets[-1]}")
        params = params if params is not None else RequestParams()
        budget = self.max_new_tokens if params.max_new_tokens is None \
            else int(params.max_new_tokens)
        if not 1 <= budget <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {budget} outside [1, "
                f"{self.max_new_tokens}] (the compiled budget; raise it "
                "in enable_generation())")
        dl = params.deadline_s if params.deadline_s is not None \
            else self.default_deadline_s
        deadline = None if dl is None \
            else time.monotonic() + float(dl)  # lint: host-sync-ok (config coercion)
        req = Request(ids, params, budget, deadline, engine=self)
        with self._qlock:
            if self._shutdown:
                req._finish(RequestStatus.REJECTED, "shutdown")
                self.stats["rejected"] += 1
                monitor.record_serve_request("rejected")
                raise RuntimeError(
                    "serving engine is shut down; no new requests")
            if len(self._queue) >= self.max_queue:
                reason = self._rejection_reason()
                req._finish(RequestStatus.REJECTED, reason)
                self.stats["rejected"] += 1
                monitor.record_serve_request("rejected")
                raise QueueFull(
                    f"request queue at bound ({self.max_queue}): "
                    f"{reason}", reason=reason, request=req)
            if self.trace_sample and req.id % self.trace_sample == 0:
                req.traced = True
                req._t_submit_ns = flight_recorder.now_ns()
            self._queue.append(req)
            self.stats["submitted"] += 1
            qdepth = len(self._queue)
            monitor.record_serve_queue_depth(qdepth)
        if flight_recorder.enabled:
            flight_recorder.record("serve.submit", req=req.id,
                                   prompt_len=int(ids.size),
                                   budget=budget, queue_depth=qdepth)
        return req

    def _rejection_reason(self) -> str:
        """The structured health reason a queue-bound rejection carries
        on BOTH the handle and the QueueFull (callers hold ``_qlock``):
        the same no_free_pages/no_free_slots distinction ``health()``
        suffixes onto its 503 reason, observable per-request — a
        router re-routes memory pressure and slot pressure to a
        different survivor set. Bare ``queue_full`` means the blocker
        is not yet known (a submit burst filled the queue between
        scheduler steps while slots were still free)."""
        if self._alloc is not None and self._page_blocked:
            return "queue_full:no_free_pages"
        if sum(s is not None for s in self._slots) >= self.max_batch:
            return "queue_full:no_free_slots"
        return "queue_full"

    def _queue_room(self) -> bool:
        with self._qlock:
            return len(self._queue) < self.max_queue

    @property
    def busy(self) -> bool:
        """True while anything is queued or occupies a slot."""
        with self._qlock:
            if self._queue:
                return True
        return any(s is not None for s in self._slots)

    # -------------------------------------------------------- scheduler
    def step(self):
        """One scheduler iteration: admit queued requests into free
        slots (short prompts inline, long ones one CHUNK per iteration
        when chunked prefill is on), dispatch one fixed-batch decode
        step for the running slots, advance the in-flight chunked
        prefill, poll completions every ``poll_every`` steps. Decode
        dispatches BEFORE the chunk's blocking sync, so in-flight
        streams overlap the chunk's device time instead of stalling
        behind a whole long prefill — the head-of-line fix."""
        with self._pump_lock:
            self._admit_ready()
            if any(s is not None
                   and s.status is RequestStatus.RUNNING
                   for s in self._slots):
                self._dispatch_decode()
            self._advance_chunked()
            if self._steps_since_poll >= self.poll_every:
                self._poll()

    def _unblock_if(self, req: Request):
        """Clear the page-pressure flag when the request it was
        computed FOR leaves the queue (deadline sweep, drain): a stale
        flag would steer the router's no_free_pages/no_free_slots
        signal at the next health() until a slot freed."""
        if self._alloc is not None and self._blocked_key is not None \
                and self._blocked_key[0] == req.id:
            self._blocked_key = None
            self._page_blocked = False

    def _pop_queue(self) -> Optional[Request]:
        with self._qlock:
            while self._queue:
                req = self._queue[0]
                if req.deadline is not None and \
                        time.monotonic() > req.deadline:
                    self._queue.popleft()
                    monitor.record_serve_queue_depth(len(self._queue))
                    self._unblock_if(req)
                    self._cancel(req, "deadline")
                    continue
                if self._needs_chunk(req) and self._chunking is not None:
                    # ONE chunked prefill at a time, strict FIFO: the
                    # long head waits (un-popped, pages uncommitted)
                    # until the active chunked admission finishes —
                    # admitting a later request past it would starve it
                    return None
                if self._alloc is not None:
                    # admission counts FREE PAGES, not just free slots:
                    # the head request's page plan (its prompt prefix
                    # hashed against the registry, its own budget +
                    # speculative overhang) must commit before the slot
                    # is spent. A pool too full leaves the head QUEUED —
                    # memory pressure, which health() reports as
                    # no_free_pages so a router can tell it from
                    # slot/admission pressure. While the pool state is
                    # UNCHANGED since the head last failed to commit,
                    # the pump loop skips the (identical) replan
                    # entirely instead of burning hash+registry walks
                    # every sub-millisecond iteration.
                    ver = self._alloc.version
                    if self._blocked_key == (req.id, ver):
                        self._page_blocked = True
                        return None
                    plan = self._alloc.plan(
                        req.prompt, req.budget + self._overhang)
                    pages = self._alloc.commit(plan)
                    if pages is None:
                        # a failed commit may still have reclaimed
                        # cached pages — key on the post-attempt version
                        self._blocked_key = (req.id, self._alloc.version)
                        self._page_blocked = True
                        return None
                    self._blocked_key = None
                    self._page_blocked = False
                    self._pending_pages[req.id] = (pages, plan)
                self._queue.popleft()
                monitor.record_serve_queue_depth(len(self._queue))
                return req
        return None

    def _needs_chunk(self, req: Request) -> bool:
        """Chunked admission applies to prompts LONGER than one chunk
        (shorter ones inline-prefill in a single dispatch, as before)."""
        return self._chunk_enabled and \
            req.prompt.size > self.prefill_chunk_tokens

    def _admit_ready(self):
        for slot, occupant in enumerate(self._slots):
            if occupant is not None:
                continue
            req = self._pop_queue()
            if req is None:
                break
            try:
                if self._needs_chunk(req):
                    self._begin_chunked(req, slot)
                else:
                    self._admit(req, slot)
            except Exception as e:
                # the request left the queue but reached no slot: it
                # MUST still go terminal or its Future would hang
                # forever (and its committed pages must return to the
                # free list); the engine keeps serving the others
                if self._chunking is not None and \
                        self._chunking["req"] is req:
                    self._chunking = None
                    self._slots[slot] = None  # lint: lock-discipline-ok (admission runs under the caller's pump lock)
                self._release_pending(req)
                self._cancel(req, f"admission error: "
                                  f"{type(e).__name__}: {e}",
                             label="error")
                monitor.record_swallowed("serving.admit", e)

    def _admit(self, req: Request, slot: int):
        # admission wall time is compute in the goodput ledger — or
        # compile, when the dispatch retraced (a cold bucket slipping
        # past warmup spends the window tracing, not prefilling)
        retraces0 = monitor.retrace_count()
        t_admit = time.perf_counter()
        try:
            self._admit_inner(req, slot)
        finally:
            dt = time.perf_counter() - t_admit
            # cost attribution mirrors the ledger charge: the request
            # owns exactly the admission wall the ledger books, so
            # per-request costs reconcile against the compute bucket
            req._cost_prefill_s += dt
            self._goodput.charge(
                "compile" if monitor.retrace_count() > retraces0
                else "compute", dt)

    def _admit_inner(self, req: Request, slot: int):
        bucket = next(b for b in self.buckets if b >= req.prompt.size)
        ids = np.full((1, bucket), self._cfg.pad_value, np.int32)
        ids[0, :req.prompt.size] = req.prompt
        plen = np.array([req.prompt.size], np.int32)
        t_admit_ns = flight_recorder.now_ns() if req.traced else 0
        exe = self._exe_prefill(bucket)
        tok, row_cache, self._key, fin = exe(
            self._state, jnp.asarray(ids), jnp.asarray(plen), self._key)
        # TTFT measurement point: the request's first token exists once
        # the prefill lands — one small sync per ADMISSION (not per
        # decode step)
        tok.block_until_ready()
        now = time.monotonic()
        req.admitted_at = req.first_token_at = now
        monitor.record_serve_ttft(now - req.submitted_at)
        if flight_recorder.enabled:
            flight_recorder.record("serve.admit", req=req.id, slot=slot,
                                   bucket=bucket)
        if req.traced:
            # the sampled request's first two trace segments: time spent
            # queued, then the (synchronous) prefill-into-slot
            t1 = flight_recorder.now_ns()
            req.span("queue_wait", req._t_submit_ns, t_admit_ns)
            req.span("prefill", t_admit_ns, t1, bucket=bucket, slot=slot)
            req._t_seg_ns = t1
        monitor.record_generation(prefill_steps=1)
        self.stats["prefills"] += 1
        admit = self._exe_admit()
        paged_args, pages, plan = (), None, None
        if self._alloc is not None:
            # the row's page table: shared prefix pages first (position
            # order), then the freshly allocated private ones; unused
            # table slots stay 0 (the null page). start marks the first
            # position the install actually writes — everything below
            # it is referenced shared content. The pending entry is
            # popped only AFTER the install lands: an admit failure
            # must leave it for _release_pending to roll back.
            pages, plan = self._pending_pages[req.id]
            table_np = np.zeros((self.pages_per_row,), np.int32)
            table_np[:len(pages)] = pages
            paged_args = (jnp.asarray(table_np),
                          jnp.asarray(plan.shared_len, jnp.int32))
        if self._spec is None:
            (self._cache, self._tok, self._finished, self._steps,
             self._budget, self._out_buf) = admit(
                self._cache, self._tok, self._finished, self._steps,
                self._budget, self._out_buf,
                jnp.asarray(slot, jnp.int32), row_cache, tok, fin,
                jnp.asarray(req.budget, jnp.int32), *paged_args)
        else:
            # the drafter's corpus row: the full-width padded prompt
            # (the admit program appends the prefill token in-trace)
            ids_row = np.full((self.max_len,), self._cfg.pad_value,
                              np.int32)
            ids_row[:req.prompt.size] = req.prompt
            (self._cache, self._tok, self._finished, self._steps,
             self._budget, self._out_buf, self._tok_buf,
             self._tok_len) = admit(
                self._cache, self._tok, self._finished, self._steps,
                self._budget, self._out_buf,
                jnp.asarray(slot, jnp.int32), row_cache, tok, fin,
                jnp.asarray(req.budget, jnp.int32), *paged_args,
                self._tok_buf, self._tok_len, jnp.asarray(ids_row),
                jnp.asarray(req.prompt.size, jnp.int32))
        if self._alloc is not None:
            # the row now references its pages; register the prompt's
            # full pages so later identical prefixes hit them
            self._pending_pages.pop(req.id)
            self._alloc.register(plan, pages)
            self._row_pages[slot] = pages
        if self._slot_used[slot]:
            self.stats["slots_reused"] += 1
        self._slot_used[slot] = True  # lint: lock-discipline-ok (admission runs under the caller's pump lock)
        self._slots[slot] = req  # lint: lock-discipline-ok (admission runs under the caller's pump lock)
        req.status = RequestStatus.RUNNING
        self.stats["admitted"] += 1
        monitor.record_serve_slot_occupancy(
            sum(s is not None for s in self._slots) / self.max_batch)
        # the blocking prefill sync above must not be attributed to
        # per-token decode latency: restart the poll window so the next
        # dispatch re-anchors it (same artifact class as idle gaps)
        self._window_steps = 0

    # ------------------------------------------------- chunked prefill
    def _begin_chunked(self, req: Request, slot: int):
        """Reserve ``slot`` for a long prompt and park it in
        PENDING_PREFILL: the device lane stays masked (finished True,
        kv_len 0, null page table) while ``_advance_chunked`` feeds the
        prompt into the side cache one chunk per scheduler iteration.
        Host bookkeeping only — no dispatch happens here."""
        C = self.prefill_chunk_tokens
        plen = int(req.prompt.size)
        n = -(-plen // C)
        ids = np.full((1, n * C), self._cfg.pad_value, np.int32)
        ids[0, :plen] = req.prompt
        shared = 0
        if self._alloc is not None:
            shared = int(self._pending_pages[req.id][1].shared_len)
        t_ns = flight_recorder.now_ns() if req.traced else 0
        if req.traced:
            req.span("queue_wait", req._t_submit_ns, t_ns)
        self._chunking = dict(req=req, slot=slot, plen=plen, n=n,
                              next=0, ids=ids, shared=shared,
                              decode_steps=0, t_ns=t_ns)
        self._slots[slot] = req  # lint: lock-discipline-ok (admission runs under the caller's pump lock)
        req.status = RequestStatus.PENDING_PREFILL
        monitor.record_serve_slot_occupancy(
            sum(s is not None for s in self._slots) / self.max_batch)

    def _advance_chunked(self):
        """Run AT MOST ONE chunk of the in-flight chunked prefill: the
        chunk program over the side cache (plus the paged span install),
        one blocking sync, then hand the device back to decode. The
        final chunk samples the first token and runs the ordinary admit
        program — TTFT lands there. Deadline/abort semantics live here
        because ``_poll`` skips PENDING_PREFILL slots entirely."""
        st = self._chunking
        if st is None:
            return
        req = st["req"]
        if req.deadline is not None and \
                time.monotonic() > req.deadline:
            self._abort_chunked("deadline")
            return
        # same goodput/cost contract as _admit: each chunk's dispatch
        # wall is compute (or compile, when it retraced), charged to
        # the request's prefill cost — chunked admissions sum their
        # per-chunk walls instead of under-charging one instant
        retraces0 = monitor.retrace_count()
        t0 = time.perf_counter()
        try:
            if st["next"] < st["n"] - 1:
                self._chunk_step(st)
            else:
                self._finish_chunked(st)
        except Exception as e:
            self._abort_chunked(
                f"admission error: {type(e).__name__}: {e}",
                label="error")
            monitor.record_swallowed("serving.admit", e)
        finally:
            dt = time.perf_counter() - t0
            req._cost_prefill_s += dt
            self._goodput.charge(
                "compile" if monitor.retrace_count() > retraces0
                else "compute", dt)
            # the blocking chunk sync must not be attributed to
            # per-token decode latency: re-anchor the poll window
            # (the same artifact class as inline admission)
            self._window_steps = 0

    def _chunk_step(self, st: dict):
        """One non-final chunk: side-cache forward, paged span install,
        blocking sync, telemetry."""
        req, slot, k = st["req"], st["slot"], st["next"]
        C = self.prefill_chunk_tokens
        t_ns = flight_recorder.now_ns() if req.traced else 0
        ids = jnp.asarray(st["ids"][:, k * C:(k + 1) * C])
        self._row_cache = self._exe_chunk()(
            self._state, ids, self._row_cache)
        if self._alloc is not None:
            # commit the chunk's positions into the planned pages now —
            # only the span at/past the shared prefix (and past already
            # installed chunks) is written; the table/kv_len install
            # waits for the final admit
            start = max(k * C, st["shared"])
            if (k + 1) * C > start:
                pages = self._pending_pages[req.id][0]
                table_np = np.zeros((self.pages_per_row,), np.int32)
                table_np[:len(pages)] = pages
                self._cache = self._exe_span()(
                    self._cache, self._row_cache,
                    jnp.asarray(table_np),
                    jnp.asarray(start, jnp.int32))
        # the chunk must LAND before the host moves on: the sync point
        # is what bounds how long a chunk can monopolize the device
        # between decode dispatches
        self._row_cache.kv_len.block_until_ready()  # lint: host-sync-ok (one sync per prefill chunk, the interleave cadence)
        st["next"] = k + 1
        tokens = min(C, st["plen"] - k * C)
        self.stats["prefill_chunks"] += 1
        monitor.record_prefill_chunk(tokens)
        if flight_recorder.enabled:
            flight_recorder.record(
                "serve.prefill_chunk", req=req.id, slot=slot, chunk=k,
                start=k * C, tokens=tokens, remaining=st["n"] - k - 1)
        if req.traced:
            req.span("prefill_chunk", t_ns, flight_recorder.now_ns(),
                     chunk=k, slot=slot, tokens=tokens)

    def _finish_chunked(self, st: dict):
        """The final (padded) chunk + admission: sample the first token
        (TTFT), install the side cache into the slot through the
        ordinary admit program, flip the request to RUNNING, rebuild
        the (donated) side cache for the next chunked admission."""
        req, slot, k = st["req"], st["slot"], st["n"] - 1
        C = self.prefill_chunk_tokens
        t_ns = flight_recorder.now_ns() if req.traced else 0
        ids = jnp.asarray(st["ids"][:, k * C:(k + 1) * C])
        plen = jnp.asarray(np.array([st["plen"]], np.int32))
        tok, row_cache, self._key, fin = self._exe_chunk_final()(
            self._state, ids, plen, self._key, self._row_cache)
        self._row_cache = row_cache
        # TTFT measurement point — same contract as inline admission
        tok.block_until_ready()  # lint: host-sync-ok (TTFT measurement point, one per admission)
        now = time.monotonic()
        req.admitted_at = req.first_token_at = now
        monitor.record_serve_ttft(now - req.submitted_at)
        tokens = st["plen"] - k * C
        self.stats["prefill_chunks"] += 1
        monitor.record_prefill_chunk(tokens)
        monitor.record_prefill_interleave(
            st["decode_steps"] / st["n"])
        if flight_recorder.enabled:
            flight_recorder.record(
                "serve.prefill_chunk", req=req.id, slot=slot, chunk=k,
                start=k * C, tokens=tokens, remaining=0)
            flight_recorder.record("serve.admit", req=req.id, slot=slot,
                                   bucket=st["n"] * C, chunks=st["n"])
        if req.traced:
            t1 = flight_recorder.now_ns()
            req.span("prefill_chunk", t_ns, t1, chunk=k, slot=slot,
                     tokens=tokens)
            req._t_seg_ns = t1
        monitor.record_generation(prefill_steps=1)
        self.stats["prefills"] += 1
        admit = self._exe_admit()
        paged_args, pages, plan = (), None, None
        if self._alloc is not None:
            # every span below the last chunk boundary is already
            # installed: the admit's install_row writes only the final
            # span (start = the later of shared prefix end and the
            # final chunk's base)
            pages, plan = self._pending_pages[req.id]
            table_np = np.zeros((self.pages_per_row,), np.int32)
            table_np[:len(pages)] = pages
            start = max(int(plan.shared_len), k * C)
            paged_args = (jnp.asarray(table_np),
                          jnp.asarray(start, jnp.int32))
        if self._spec is None:
            (self._cache, self._tok, self._finished, self._steps,
             self._budget, self._out_buf) = admit(
                self._cache, self._tok, self._finished, self._steps,
                self._budget, self._out_buf,
                jnp.asarray(slot, jnp.int32), self._row_cache, tok, fin,
                jnp.asarray(req.budget, jnp.int32), *paged_args)
        else:
            ids_row = np.full((self.max_len,), self._cfg.pad_value,
                              np.int32)
            ids_row[:req.prompt.size] = req.prompt
            (self._cache, self._tok, self._finished, self._steps,
             self._budget, self._out_buf, self._tok_buf,
             self._tok_len) = admit(
                self._cache, self._tok, self._finished, self._steps,
                self._budget, self._out_buf,
                jnp.asarray(slot, jnp.int32), self._row_cache, tok, fin,
                jnp.asarray(req.budget, jnp.int32), *paged_args,
                self._tok_buf, self._tok_len, jnp.asarray(ids_row),
                jnp.asarray(req.prompt.size, jnp.int32))
        if self._alloc is not None:
            self._pending_pages.pop(req.id)
            self._alloc.register(plan, pages)
            self._row_pages[slot] = pages
        if self._slot_used[slot]:
            self.stats["slots_reused"] += 1
        self._slot_used[slot] = True  # lint: lock-discipline-ok (admission runs under the caller's pump lock)
        req.status = RequestStatus.RUNNING
        self.stats["admitted"] += 1
        self._chunking = None
        # the admit program donated the side cache: rebuild it zeroed
        # (kv_len 0, clips 0) so the next chunked admission starts
        # clean — this rebuild IS the between-requests reset
        self._row_cache = self._fresh_row_cache()
        monitor.record_serve_slot_occupancy(
            sum(s is not None for s in self._slots) / self.max_batch)

    def _abort_chunked(self, reason: str, label: Optional[str] = None):
        """Terminal exit for a mid-prefill request (deadline, drain,
        dispatch error): release its committed pages, clear the slot,
        rebuild the side cache. No free-program dispatch — the device
        lane was never installed (finished True, kv_len 0, null
        table), so there is nothing to reset."""
        st, self._chunking = self._chunking, None
        if st is None:
            return
        req, slot = st["req"], st["slot"]
        if flight_recorder.enabled:
            flight_recorder.record(
                "serve.evict", req=req.id, slot=slot, reason=reason,
                tokens=0, chunks_done=st["next"])
        self._release_pending(req)
        self._slots[slot] = None  # lint: lock-discipline-ok (abort runs under the caller's pump lock)
        # the side cache holds the aborted prompt's partial prefix —
        # rebuild zeroed before the next chunked admission
        self._row_cache = self._fresh_row_cache()
        self._cancel(req, reason, label=label)
        self._note_cost(req)

    def _dispatch_decode(self):
        exe = self._exe_step()
        if self._spec is None:
            (self._tok, self._cache, self._key, self._finished,
             self._steps, self._budget, self._out_buf) = exe(
                self._state, self._tok, self._cache, self._key,
                self._finished, self._steps, self._budget,
                self._out_buf)
        else:
            (self._tok, self._cache, self._key, self._finished,
             self._steps, self._budget, self._out_buf, self._tok_buf,
             self._tok_len, self._proposed, self._accepted) = exe(
                self._state, self._tok, self._cache, self._key,
                self._finished, self._steps, self._budget,
                self._out_buf, self._tok_buf, self._tok_len,
                self._proposed, self._accepted)
        self._steps_since_poll += 1
        if self._chunking is not None:
            # decode steps interleaved into THIS chunked admission —
            # the serve.prefill.interleave_ratio numerator
            self._chunking["decode_steps"] += 1
        if self._window_steps == 0:
            # anchor the latency window at the first dispatch after a
            # poll — idle gaps between traffic bursts must not be
            # attributed to per-token latency
            self._window_t0 = time.monotonic()
        self._window_steps += 1
        self.stats["decode_steps"] += 1
        monitor.record_generation(decode_steps=1)

    def _poll(self):
        """Scheduler poll: read the [batch] finished/step lanes (the
        only per-window host sync on the decode path), complete
        finished rows, cancel over-deadline ones, time the window."""
        self._steps_since_poll = 0
        fin = np.asarray(self._finished)  # lint: host-sync-ok (scheduler poll, every poll_every steps)
        steps = np.asarray(self._steps)  # lint: host-sync-ok (same poll read)
        if self._spec is not None:
            # drain the on-device speculation counters in the same poll
            # window (two int32 scalars — no extra sync cadence). The
            # device counters are lifetime-monotonic int32 and WRAP on
            # a long-lived engine; per-poll deltas are tiny, so modular
            # subtraction recovers them exactly across the wrap
            prop = int(np.asarray(self._proposed))  # lint: host-sync-ok (same poll read)
            acc = int(np.asarray(self._accepted))  # lint: host-sync-ok (same poll read)
            dp = (prop - self._spec_seen[0]) % (1 << 32)
            da = (acc - self._spec_seen[1]) % (1 << 32)
            if dp or da:
                self._spec_seen = (prop, acc)
                self.stats["spec_proposed"] += dp
                self.stats["spec_accepted"] += da
                monitor.record_speculative(dp, da)
        now = time.monotonic()
        window_dt = 0.0
        if self._window_t0 is not None and self._window_steps:
            window_dt = now - self._window_t0
            monitor.record_serve_token_latency(
                window_dt / self._window_steps)
            # the dispatch window (host dispatches + the device wait
            # the lane reads above just paid) is goodput compute
            self._goodput.charge("compute", window_dt)
        self._window_steps = 0   # next dispatch re-anchors _window_t0
        if window_dt > 0.0:
            # cost attribution: every live request owns an equal share
            # of the window the ledger just booked as compute (shares
            # sum to the window — Request.cost() reconciles against
            # the compute bucket), plus page*seconds for its resident
            # KV pages. Charged BEFORE completions below, so a request
            # finishing this window still pays for it.
            # PENDING_PREFILL slots are NOT in the decode window: the
            # chunk walls charge to prefill_s in _advance_chunked —
            # charging a share here would double-bill the request
            live = sum(r is not None
                       and r.status is not RequestStatus.PENDING_PREFILL
                       for r in self._slots)
            if live:
                share = window_dt / live
                for i, r in enumerate(self._slots):
                    if r is None or \
                            r.status is RequestStatus.PENDING_PREFILL:
                        continue
                    r._cost_decode_s += share
                    if self._alloc is not None:
                        pages = self._row_pages[i]
                        if pages:
                            r._cost_page_s += len(pages) * window_dt
        t_poll_ns = flight_recorder.now_ns()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.status is RequestStatus.PENDING_PREFILL:
                # mid-chunked-prefill: the lane is parked (its finished
                # flag reads True) — completion/deadline/trace handling
                # belongs to _advance_chunked, not the decode poll
                continue
            if fin[i]:
                toks = np.asarray(self._out_buf[i])[:int(steps[i])]  # lint: host-sync-ok (one row read per completion)
                self._complete(req, toks)
                # freed in place; the next admission overwrites the row
                self._slots[i] = None  # lint: lock-discipline-ok (poll runs under the caller's pump lock)
                self._free_slot_pages(i)
            elif req.deadline is not None and now > req.deadline:
                self._evict(i, req, "deadline", int(steps[i]))
            elif req.traced:
                # rolling decode segment: one span per poll window, so
                # a mid-flight dump shows how far the request got
                req.span("decode", req._t_seg_ns, t_poll_ns,
                         tokens=int(steps[i]))
                req._t_seg_ns = t_poll_ns
        # expire queued requests that can no longer meet their deadline
        with self._qlock:
            for req in list(self._queue):
                if req.deadline is not None and now > req.deadline:
                    self._queue.remove(req)
                    self._unblock_if(req)
                    self._cancel(req, "deadline")
            monitor.record_serve_queue_depth(len(self._queue))
        monitor.record_serve_slot_occupancy(
            sum(s is not None for s in self._slots) / self.max_batch)
        if monitor.enabled:
            monitor.record_cache_occupancy(self._cache.occupancy())
            self._drain_page_stats()
            self._drain_quant_stats()
            self._goodput.flush()
            # SLO watchtower: sample the time-series ring + evaluate
            # burn rates at most once per ring period (fast path is a
            # float compare — gated in test_overhead_gate)
            slo_mod.tick()

    def _complete(self, req: Request, toks: np.ndarray):
        eos = self._cfg.eos_token_id
        req.n_emitted = int(toks.size)
        n_real = int(toks.size)
        if eos is not None:
            hits = np.nonzero(toks == eos)[0]
            if hits.size:
                n_real = int(hits[0]) + 1    # the eos itself counts
                toks = toks[:int(hits[0])]   # result is eos-trimmed
        req.tokens = toks.astype(np.int32)
        monitor.record_generation(tokens=n_real)
        req._finish(RequestStatus.COMPLETED)
        self.stats["completed"] += 1
        monitor.record_serve_request("completed")
        self._note_cost(req)

    def _cancel(self, req: Request, reason: str,
                label: Optional[str] = None):
        """Terminal CANCELLED for a request not occupying a slot.
        ``label`` overrides the metric label when ``reason`` carries
        free text (error messages must not become label cardinality)."""
        req._finish(RequestStatus.CANCELLED, reason)
        self.stats["cancelled"] += 1
        monitor.record_serve_request("cancelled")
        monitor.record_serve_cancellation(label or reason)

    def _evict(self, slot: int, req: Request, reason: str,
               n_done: int = 0):
        """Cancel an in-flight request: mask its lane + reset its cache
        row via the free program, keep whatever it produced."""
        if flight_recorder.enabled:
            flight_recorder.record("serve.evict", req=req.id, slot=slot,
                                   reason=reason, tokens=n_done)
        exe = self._exe_free()
        self._cache, self._finished = exe(
            self._cache, self._finished, jnp.asarray(slot, jnp.int32))
        if n_done:
            row = np.asarray(self._out_buf[slot])  # lint: host-sync-ok (partial row on eviction)
            req.tokens = row[:n_done].astype(np.int32)
            req.n_emitted = n_done
        self._slots[slot] = None  # lint: lock-discipline-ok (eviction runs under the caller's pump lock)
        self._free_slot_pages(slot)
        self._cancel(req, reason)
        self._note_cost(req)

    def _note_cost(self, req: Request):
        """Terminal cost attribution: land the request's accumulated
        cost in the serve.cost.* histograms and keep the top-K most
        expensive requests for the /slo table."""
        c = req.cost()
        monitor.record_request_cost(c["prefill_s"], c["decode_s"],
                                    c["page_s"])
        with self._qlock:
            heapq.heappush(self._cost_top, (c["total_s"], req.id, c))
            while len(self._cost_top) > self._cost_topk:
                heapq.heappop(self._cost_top)

    def cost_table(self) -> List[dict]:
        """The top-K most expensive terminal requests, costliest
        first — the /slo endpoint's per-request attribution table."""
        with self._qlock:
            top = sorted(self._cost_top, reverse=True)
        return [dict(req=rid, **{k: round(v, 6) for k, v in c.items()})
                for _, rid, c in top]

    # ------------------------------------------------- page bookkeeping
    def _free_slot_pages(self, slot: int):
        """Return a terminal slot's page references to the allocator
        (pages referenced by other rows or cached in the prefix
        registry stay resident — that is the sharing)."""
        if self._alloc is None:
            return
        pages, self._row_pages[slot] = self._row_pages[slot], None
        if pages:
            self._alloc.free_row(pages)

    def _release_pending(self, req: Request):
        """Roll back a committed page plan whose admission failed."""
        if self._alloc is None:
            return
        ent = self._pending_pages.pop(req.id, None)
        if ent is not None:
            self._alloc.free_row(ent[0])

    def _drain_page_stats(self):
        """Forward the allocator's lifetime counters into the metrics
        registry as deltas (called at the poll cadence — host ints
        only, no device sync)."""
        if self._alloc is None:
            return
        stats = dict(self._alloc.stats)
        prev, self._page_seen = self._page_seen, stats
        delta = {k: stats[k] - prev.get(k, 0) for k in stats}
        monitor.record_paged_cache(
            allocated=delta["pages_allocated"],
            freed=delta["pages_freed"],
            prefix_hits=delta["prefix_hits"],
            shared_pages=delta["shared_pages"],
            cow_copies=delta["cow_copies"])
        monitor.record_page_occupancy(self._alloc.page_occupancy())

    def _drain_quant_stats(self):
        """Drain the quantized cache's in-device saturation counter
        into ``gen.cache.quant.scale_clips`` (one int32 scalar read at
        the poll cadence, beside the existing lane reads; the lifetime
        counter is int32 and may wrap — modular delta, same treatment
        as the speculation counters)."""
        if getattr(self._cache, "clips", None) is None:
            return
        clips = int(np.asarray(self._cache.clips))  # lint: host-sync-ok (scheduler poll, tiny scalar)
        d = (clips - self._clips_seen) % (1 << 32)
        if d:
            self._clips_seen = clips
            monitor.record_kv_quant(scale_clips=d)

    # -------------------------------------------------------- front-end
    def _submit_item(self, item) -> Request:
        if isinstance(item, tuple) and len(item) == 2 and \
                isinstance(item[1], RequestParams):
            return self.submit(item[0], item[1])
        return self.submit(item)

    def serve_forever(self, request_iter=None, *, shutdown=None,
                      on_step=None, idle_sleep_s: float = 0.0005):
        """Blocking serve loop. With ``request_iter`` it pulls prompts
        (or ``(prompt, RequestParams)`` tuples; the iterator must not
        block in ``__next__``) whenever the queue has room and returns
        the submitted handles once the iterator is exhausted and every
        request is terminal. With ``request_iter=None`` it really does
        serve forever — pumping ``submit()`` traffic from other threads
        through idle gaps — until a preemption or ``shutdown()`` ends
        it.

        Preemption: when the active ``GracefulShutdown`` context (or
        ``shutdown``) reports preempted — or ``shutdown()`` was called —
        the loop drains: queued requests get a clean REJECTED, in-flight
        slots keep decoding up to ``drain_timeout_s`` then are cancelled;
        nothing hangs. ``on_step(engine)`` runs once per loop iteration
        (traffic shaping, fault injection in tests)."""
        from ..distributed import resilience
        handles: List[Request] = []
        it = iter(request_iter) if request_iter is not None else None
        exhausted = False   # an iterator-less loop never "finishes"
        try:
            while True:
                gs = shutdown if shutdown is not None \
                    else resilience.active()
                if self._shutdown or (gs is not None and gs.preempted):
                    preempted_drain = gs is not None and \
                        gs.preempted and not self._shutdown
                    if preempted_drain:
                        # preemption landed mid-serve: leave the black
                        # box BEFORE draining, while the in-flight
                        # requests' spans still show what was running
                        flight_recorder.record(
                            "serve.preempted",
                            in_flight=sum(s is not None
                                          for s in self._slots))
                        flight_recorder.auto_dump("preemption")
                    compute0 = self._goodput.bucket_total("compute")
                    t_drain = time.perf_counter()
                    self.drain()
                    if preempted_drain:
                        # the preemption-recovery bucket gets the drain
                        # wall MINUS the decode windows that already
                        # charged compute inside it (no second count)
                        dc = self._goodput.bucket_total("compute") \
                            - compute0
                        self._goodput.charge(
                            "preemption_recovery",
                            max(time.perf_counter() - t_drain - dc,
                                0.0))
                    break
                while it is not None and not exhausted and \
                        self._queue_room():
                    try:
                        item = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    handles.append(self._submit_item(item))
                if on_step is not None:
                    on_step(self)
                if self.busy:
                    self.step()
                elif exhausted:
                    break
                else:
                    time.sleep(idle_sleep_s)
        except BaseException as e:
            # an uncaught scheduler/device error — or an operator's
            # Ctrl-C — is exactly when the flight recorder earns its
            # keep: dump, then propagate (same contract as fit();
            # SystemExit means a preemption path that already dumped)
            if not isinstance(e, SystemExit):
                flight_recorder.record(
                    "serve.crash", error=f"{type(e).__name__}: {e}")
                flight_recorder.auto_dump("serve_crash")
            raise
        return handles

    def drain(self):
        """Graceful shutdown: reject everything still queued, keep
        decoding in-flight slots until each reaches a terminal status
        or ``drain_timeout_s``, then cancel the stragglers. Every
        request ends terminal; none hang. Idempotent; the engine
        accepts no new work afterwards."""
        with self._pump_lock:
            with self._qlock:
                already = self._shutdown and not self._queue \
                    and all(s is None for s in self._slots)
                self._shutdown = True
                queued, self._queue = \
                    list(self._queue), collections.deque()
                if self._alloc is not None:
                    self._blocked_key = None
                    self._page_blocked = False
                monitor.record_serve_queue_depth(0)
            if flight_recorder.enabled and not already:
                flight_recorder.record(
                    "serve.drain_begin", queued=len(queued),
                    in_flight=sum(s is not None for s in self._slots))
            for req in queued:
                req._finish(RequestStatus.REJECTED, "shutdown")
                self.stats["rejected"] += 1
                monitor.record_serve_request("rejected")
            # a PENDING_PREFILL slot can never decode to terminal —
            # abort it NOW (pages back to the free list, request
            # CANCELLED) or the decode drain below would spin on its
            # occupied slot until the timeout
            self._abort_chunked("shutdown")
            deadline = time.monotonic() + self.drain_timeout_s
            while any(s is not None for s in self._slots) and \
                    time.monotonic() < deadline:
                self._dispatch_decode()
                if self._steps_since_poll >= self.poll_every:
                    self._poll()
            if any(s is not None for s in self._slots):
                # final poll before declaring stragglers: rows that
                # finished since the last cadence poll must complete,
                # not get mislabeled CANCELLED
                self._poll()
            steps = np.asarray(self._steps)  # lint: host-sync-ok (drain-cutoff lane read)
            for i, req in enumerate(self._slots):
                if req is not None:
                    self._evict(i, req, "shutdown", int(steps[i]))
            monitor.record_serve_slot_occupancy(0.0)
            if monitor.enabled:
                self._drain_page_stats()
                self._drain_quant_stats()
                self._goodput.flush()
            if flight_recorder.enabled and not already:
                flight_recorder.record("serve.drain_end")
            if self.fleet is not None and not already:
                # push the final counters so the aggregator's last view
                # of this replica is the drained one (thread keeps
                # running — /fleet staleness only starts at shutdown)
                try:
                    self.fleet.publisher.publish_now()
                except Exception as e:
                    monitor.record_swallowed("serving.fleet_drain", e)

    shutdown_now = drain

    # ----------------------------------------------------- thread mode
    def start(self) -> "ServingEngine":
        """Background pump thread: ``submit()``/``result()`` from any
        thread, ``shutdown()`` to drain and stop."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True, name="serving-engine")
        self._thread.start()
        return self

    def _run_loop(self):
        while not self._shutdown:
            if self.busy:
                with self._pump_lock:
                    if not self._shutdown:
                        self.step()
            else:
                time.sleep(0.001)

    def shutdown(self):
        """Drain (every request terminal), stop the pump thread, and
        release the telemetry port. drain() alone deliberately keeps
        the server up — a post-drain scrape is how the fleet observes
        the exit — but full shutdown() must free the port so a
        relaunched engine on the same fixed port can bind."""
        self.drain()
        if self._thread is not None:
            self._thread.join(timeout=self.drain_timeout_s + 5.0)
            self._thread = None
        self._goodput.close()
        if self.fleet is not None:
            self.fleet.stop()   # final publish rides in stop()
            self.fleet = None
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None

    def _try_pump(self) -> bool:
        """Inline pump for handle.result() when no thread owns the
        engine; returns True when it made progress."""
        if self._thread is not None and self._thread.is_alive():
            return False
        if not self._pump_lock.acquire(blocking=False):
            return False
        try:
            if self.busy and not self._shutdown:
                self.step()
                return True
            return False
        finally:
            self._pump_lock.release()

    def goodput(self) -> Dict:
        """The serve-side goodput decomposition right now:
        ``{"wall_s", "buckets", "goodput_fraction"}`` with every
        bucket summing to wall time (bench's ``"goodput"`` sub-dict,
        and the tier-1 ledger-invariant gate)."""
        return self._goodput.snapshot()

    # ----------------------------------------------------------- health
    def health(self) -> Dict:
        """Readiness snapshot for the telemetry server's ``/readyz``:
        ready iff warm (every program compiled/loaded), not draining/
        shut down, and the queue is below its bound — the backpressure
        signal a multi-replica router needs to stop sending traffic
        BEFORE submits start raising QueueFull. Always includes the
        capacity detail (queue depth, slot occupancy) so a 503 is
        self-explaining."""
        with self._qlock:
            depth = len(self._queue)
        busy = sum(s is not None for s in self._slots)
        paged = self._alloc is not None
        # what the queue head is actually waiting on: "pages" = the
        # pool could not cover its plan (MEMORY pressure — more HBM or
        # fewer/shorter requests would help), "slots" = every decode
        # lane is busy (ADMISSION capacity — another replica would
        # help). The distinction is what the multi-replica router
        # routes on; it also suffixes the 503 reason below.
        blocked_on = None
        if depth:
            if paged and self._page_blocked:
                blocked_on = "pages"
            elif busy >= self.max_batch:
                blocked_on = "slots"
        reasons = []
        if self._shutdown:
            reasons.append("draining")
        if not self._warm:
            reasons.append("warming")
        if depth >= self.max_queue:
            # suffix the blocker only when it is actually known — a
            # submit burst can fill the queue between scheduler steps
            # while slots are still free
            reasons.append("queue_full" if blocked_on is None
                           else f"queue_full:no_free_{blocked_on}")
        # effective cache capacity in TOKENS (PR-12's named remainder):
        # pool pages x page size for the paged cache, slots x max_len
        # dense — REAL headroom, already adjusted for the cache dtype
        # because an int8 pool configured at equal HBM holds ~2x the
        # pages/slots of a bf16 one. The kv_dtype label rides along so
        # the item-1 router can compare replicas across precisions.
        if paged:
            cap_tokens = (self._alloc.n_pages - 1) * self.page_size
            free_tokens = self._alloc.free_pages() * self.page_size
        else:
            cap_tokens = self.max_batch * self.max_len
            free_tokens = (self.max_batch - busy) * self.max_len
        # prefill backlog (chunked admission in flight): prompt tokens
        # not yet written to the KV cache + chunks still to run. The
        # fleet router folds this into its score so long prompts steer
        # away from a replica that is mid-prefill — its next chunks
        # will keep taxing every decode window it serves.
        pp_tokens = pp_chunks = 0
        st = self._chunking
        if st is not None:
            pp_tokens = max(
                0, st["plen"] - st["next"] * self.prefill_chunk_tokens)
            pp_chunks = st["n"] - st["next"]
        return {
            "ready": not reasons,
            **({"reason": ",".join(reasons)} if reasons else {}),
            "queue_depth": depth, "max_queue": self.max_queue,
            "queue_blocked_on": blocked_on,
            "slots_busy": busy, "max_batch": self.max_batch,
            "free_slots": self.max_batch - busy,
            "kv_cache_dtype": self._kv_dtype_label,
            "capacity_tokens": cap_tokens,
            "free_tokens": free_tokens,
            "pending_prefill_tokens": pp_tokens,
            "prefill_chunks_queued": pp_chunks,
            **({"free_pages": self._alloc.free_pages(),
                "total_pages": self._alloc.n_pages - 1,
                "page_occupancy": round(
                    self._alloc.page_occupancy(), 4)} if paged else {}),
            # static HBM plan (computed when a budget gates the engine,
            # or on the first memory_plan() call): the router can admit
            # on PREDICTED headroom instead of discovering an OOM
            **({"predicted_peak_bytes":
                    self._mem_summary["predicted_peak_bytes"],
                **({"hbm_budget": self.hbm_budget,
                    "predicted_headroom_bytes":
                        self.hbm_budget
                        - self._mem_summary["predicted_peak_bytes"]}
                   if self.hbm_budget is not None else {})}
               if self._mem_summary is not None else {}),
            "warm": self._warm, "draining": self._shutdown,
        }

    # ---------------------------------------------------- memory plan
    def memory_plan(self) -> Dict:
        """Predicted HBM footprint of this engine, from the static
        planner (``analysis.plan_memory`` — trace-only, nothing
        executes): the decode program's peak at the TPU donation
        intent (weights + kv cache + lanes resident, in-place via
        donation) and the admission transient (a batch-1 prefill at
        the largest bucket runs WHILE the engine state is resident —
        its peak minus the shared weights rides on top). Returns the
        byte breakdown plus the two :class:`analysis.MemoryPlan`\\ s;
        cached after the first call. The constructor validates this
        against ``hbm_budget`` and ``health()`` exports the headroom."""
        if self._mem_summary is not None:
            return self._mem_summary
        from ..analysis import plan_memory
        self._ensure_eval()
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(tuple(v.shape), v.dtype) for v in self._state)
        key = sds((2,), jnp.uint32)
        if self._spec is None:
            decode = plan_memory(
                self._step_fn, state, self._tok, self._cache, key,
                self._finished, self._steps, self._budget,
                self._out_buf, self._cfg, static_argnums=(8,),
                donate=self._step_donate_intent,
                name="serving.decode")
        else:
            decode = plan_memory(
                self._step_fn, state, self._tok, self._cache, key,
                self._finished, self._steps, self._budget,
                self._out_buf, self._tok_buf, self._tok_len,
                self._proposed, self._accepted, self._cfg, self._spec,
                static_argnums=(12, 13),
                donate=self._step_donate_intent,
                name="serving.decode")
        prefill = plan_memory(
            self._prefill_fn, state, sds((1, self.buckets[-1]),
                                         jnp.int32),
            sds((1,), jnp.int32), key, self._cfg, self.max_len,
            static_argnums=(4, 5),
            name=f"serving.prefill.{self.buckets[-1]}")
        chunk = None
        if self._chunk_enabled:
            # the chunk program's transient rides on top of the SAME
            # resident engine state as an inline admission — plus it
            # keeps the side cache resident between chunks (an operand
            # of the plan, so its bytes are inside chunk.peak_bytes)
            chunk = plan_memory(
                self._chunk_fn, state,
                sds((1, self.prefill_chunk_tokens), jnp.int32),
                self._row_cache_aval,
                donate=self._chunk_donate_intent,
                name=f"serving.prefill_chunk."
                     f"{self.prefill_chunk_tokens}")
        if decode.arg_bytes is not None:
            weights = decode.arg_bytes[0]
            kv = decode.arg_bytes[2]
            lanes = sum(decode.arg_bytes) - weights - kv
            resident = sum(decode.arg_bytes)
            predicted = max(decode.peak_bytes,
                            resident + prefill.peak_bytes - weights)
            if chunk is not None:
                predicted = max(
                    predicted, resident + chunk.peak_bytes - weights)
        else:
            # exotic-pytree fail-safe (audit couldn't line leaves up
            # with positional args): no per-operand breakdown, and the
            # prefill transient can't subtract the shared weights —
            # predict CONSERVATIVELY rather than crash or under-gate
            weights = kv = lanes = None
            predicted = max(decode.peak_bytes,
                            decode.args_bytes + prefill.peak_bytes)
            if chunk is not None:
                predicted = max(predicted,
                                decode.args_bytes + chunk.peak_bytes)
        self._mem_summary = {
            "weights_bytes": weights, "kv_cache_bytes": kv,
            "lanes_bytes": lanes,
            "decode_peak_bytes": decode.peak_bytes,
            "prefill_peak_bytes": prefill.peak_bytes,
            **({"chunk_peak_bytes": chunk.peak_bytes}
               if chunk is not None else {}),
            "predicted_peak_bytes": predicted,
            "plans": {"decode": decode, "prefill": prefill,
                      **({"chunk": chunk} if chunk is not None else {})},
        }
        return self._mem_summary

    # ------------------------------------------------------------ audit
    def audit(self, **audit_kw) -> Dict:
        """Static audit of every program the scheduler dispatches: one
        prefill report per bucket plus the decode/admit/free trio
        (analysis.audit over abstract operands — nothing executes).
        The slot-decode and admit programs are audited with the TPU
        donation INTENT (KV cache + every token/flag lane donated) even
        on CPU; the tier-1 gate asserts zero ERROR findings everywhere
        and donation coverage 1.0 on the slot-decode program — the
        cache and token buffers must stay in-place across scheduler
        steps."""
        from ..analysis import audit as _audit
        # audit must describe the EVAL program the engine serves, even
        # when called mid-fit on a shared layer
        self._ensure_eval()
        base = audit_kw.pop("name", "serving")
        sds = jax.ShapeDtypeStruct
        state = tuple(sds(tuple(v.shape), v.dtype) for v in self._state)
        key = sds((2,), jnp.uint32)
        reports: Dict = {}
        for b in self.buckets:
            reports[("prefill", b)] = _audit(
                self._prefill_fn, state, sds((1, b), jnp.int32),
                sds((1,), jnp.int32), key, self._cfg, self.max_len,
                static_argnums=(4, 5), name=f"{base}.prefill.{b}",
                **audit_kw)
        # decode avals are the engine's own lanes; the row-cache aval
        # comes from the smallest bucket's prefill report (same trace)
        tok_a, row_cache_a, _, fin_a = \
            reports[("prefill", self.buckets[0])].out_shape
        scalar = sds((), jnp.int32)
        # the paged admit carries the row's page table + install start
        # after row_budget; its donation set is the same (the pool
        # pytree and every lane stay in place across admissions)
        paged_a = () if self._alloc is None else (
            sds((self.pages_per_row,), jnp.int32), scalar)
        if self._spec is None:
            reports["decode"] = _audit(
                self._step_fn, state, self._tok, self._cache, self._key,
                self._finished, self._steps, self._budget, self._out_buf,
                self._cfg, static_argnums=(8,),
                donate=self._step_donate_intent,
                name=f"{base}.decode", **audit_kw)
            reports["admit"] = _audit(
                self._admit_fn, self._cache, self._tok, self._finished,
                self._steps, self._budget, self._out_buf, scalar,
                row_cache_a, tok_a, fin_a, scalar, *paged_a,
                donate=self._admit_donate_intent,
                name=f"{base}.admit", **audit_kw)
        else:
            # the speculative step IS the decode program the scheduler
            # dispatches: fused ngram draft + single-dispatch verify,
            # every state lane (cache, token buffers, counters) donated
            reports["decode"] = _audit(
                self._step_fn, state, self._tok, self._cache, self._key,
                self._finished, self._steps, self._budget, self._out_buf,
                self._tok_buf, self._tok_len, self._proposed,
                self._accepted, self._cfg, self._spec,
                static_argnums=(12, 13),
                donate=self._step_donate_intent,
                name=f"{base}.decode", **audit_kw)
            reports["admit"] = _audit(
                self._admit_fn, self._cache, self._tok, self._finished,
                self._steps, self._budget, self._out_buf, scalar,
                row_cache_a, tok_a, fin_a, scalar, *paged_a,
                self._tok_buf, self._tok_len,
                sds((self.max_len,), jnp.int32), scalar,
                donate=self._admit_donate_intent,
                name=f"{base}.admit", **audit_kw)
        reports["free"] = _audit(
            self._free_fn, self._cache, self._finished, scalar,
            donate=self._free_donate_intent, name=f"{base}.free",
            **audit_kw)
        if self._chunk_enabled:
            # the chunk-prefill pair (and the paged span install) join
            # the audited program set: the tier-1 ledger drift gate and
            # the donation-coverage gate extend to them — the side
            # cache must round-trip IN PLACE every chunk
            C = self.prefill_chunk_tokens
            rc_a = self._row_cache_aval
            reports[("chunk", C)] = _audit(
                self._chunk_fn, state, sds((1, C), jnp.int32), rc_a,
                donate=self._chunk_donate_intent,
                name=f"{base}.prefill_chunk.{C}", **audit_kw)
            reports[("chunk_final", C)] = _audit(
                self._chunk_final_fn, state, sds((1, C), jnp.int32),
                sds((1,), jnp.int32), key, rc_a, self._cfg,
                static_argnums=(5,),
                donate=self._chunk_final_donate_intent,
                name=f"{base}.prefill_chunk_final.{C}", **audit_kw)
            if self._alloc is not None:
                reports[("install_span",)] = _audit(
                    self._span_fn, self._cache, rc_a,
                    sds((self.pages_per_row,), jnp.int32), scalar,
                    donate=self._span_donate_intent,
                    name=f"{base}.install_span", **audit_kw)
        return reports

    def __repr__(self):
        occ = sum(s is not None for s in self._slots)
        with self._qlock:
            q = len(self._queue)
        paged = "" if self._alloc is None else \
            (f", pages={self._alloc.used_pages()}"
             f"/{self._alloc.n_pages - 1}x{self.page_size}")
        return (f"ServingEngine(slots={occ}/{self.max_batch}, "
                f"queued={q}, buckets={self.buckets}, "
                f"cache_len={self.max_len}{paged}, "
                f"warm={self._warm}, shutdown={self._shutdown})")
