"""Serving request front-end: Future-style handles + admission types.

A :class:`Request` is both the scheduler's bookkeeping record and the
caller's handle: ``submit()`` returns it immediately, ``result()``
blocks until the request reaches a terminal status (pumping the engine
inline when no background pump thread owns it, so a single-threaded
caller can ``submit(); result()`` without deadlocking).

Terminal statuses and how a request gets there:

    COMPLETED   decoded to eos or its token budget
    CANCELLED   deadline expired (queued or mid-decode), or the drain
                timeout hit during a graceful shutdown
    REJECTED    queue at bound when submitted, or still queued when a
                shutdown drain started

``result()`` returns the generated token ids for COMPLETED and raises
:class:`RequestFailed` otherwise (partial tokens, if any, stay on
``handle.tokens``).
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
import os
import threading
import time
from typing import Optional

import numpy as np

from ..core import flight_recorder

__all__ = ["QueueFull", "Request", "RequestFailed", "RequestParams",
           "RequestStatus"]


class RequestStatus(str, enum.Enum):
    QUEUED = "queued"
    #: chunked prefill in flight: the request owns a slot (and its
    #: committed pages) but its prompt is only partially written — the
    #: scheduler never decodes a PENDING_PREFILL slot; the final chunk's
    #: admission flips it to RUNNING
    PENDING_PREFILL = "pending_prefill"
    RUNNING = "running"
    COMPLETED = "completed"
    CANCELLED = "cancelled"
    REJECTED = "rejected"

    @property
    def terminal(self) -> bool:
        return self in (RequestStatus.COMPLETED, RequestStatus.CANCELLED,
                        RequestStatus.REJECTED)


@dataclasses.dataclass(frozen=True)
class RequestParams:
    """Per-request knobs. ``max_new_tokens`` must not exceed the
    engine's compiled budget (the out-buffer width); ``deadline_s`` is
    relative to submit time — a request still queued or still decoding
    past it is cancelled with a timeout status."""
    max_new_tokens: Optional[int] = None
    deadline_s: Optional[float] = None


class QueueFull(RuntimeError):
    """Admission control: the request queue is at its depth bound.

    ``reason`` carries the structured health reason — ``queue_full``
    (blocker not yet known: a submit burst between scheduler steps),
    ``queue_full:no_free_slots`` (admission capacity — another replica
    would help), ``queue_full:no_free_pages`` (KV memory pressure —
    only a replica with pool headroom helps) — and ``request`` the
    already-terminal REJECTED handle, so a router or external LB can
    tell retryable pressure from a terminal drain without parsing the
    message."""

    def __init__(self, msg: str = "", *, reason: str = "queue_full",
                 request: Optional["Request"] = None):
        super().__init__(msg)
        self.reason = reason
        self.request = request


class RequestFailed(RuntimeError):
    """result() on a request that did not complete."""

    def __init__(self, status: RequestStatus, detail: str):
        super().__init__(f"request {status.value}: {detail}")
        self.status = status
        self.detail = detail


_ids = itertools.count()


class Request:
    """One submitted prompt: scheduler record + caller handle."""

    def __init__(self, prompt: np.ndarray, params: RequestParams,
                 budget: int, deadline: Optional[float], engine=None):
        self.id = next(_ids)
        self.prompt = prompt                  # [plen] int32
        self.params = params
        self.budget = int(budget)             # tokens incl. the prefill one
        self.deadline = deadline              # absolute monotonic, or None
        self.status = RequestStatus.QUEUED
        self.detail = ""
        self.tokens: Optional[np.ndarray] = None   # eos-trimmed on success
        self.n_emitted = 0                    # raw tokens incl. eos
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._engine = engine
        self._event = threading.Event()
        # ---- per-request tracing (tentpole 2): every request carries a
        # trace id; SAMPLED requests (the engine sets traced=True for
        # 1-in-N) additionally record queue-wait/prefill/decode spans
        # into the flight recorder, so a dump or a Perfetto export shows
        # what each in-flight request was doing. The off path is one
        # attribute check (gated by test_overhead_gate).
        self.trace_id = f"{os.getpid():x}.{self.id}"
        self.traced = False
        self._t_submit_ns = 0   # set by the engine when traced
        self._t_seg_ns = 0      # rolling decode-segment anchor
        # ---- cost attribution (SLO watchtower): the engine charges
        # prefill wall at admission, this request's share of every poll
        # window it was live in, and page*seconds held in the paged
        # pool; read back via cost() and the /slo top-K table
        self._cost_prefill_s = 0.0
        self._cost_decode_s = 0.0
        self._cost_page_s = 0.0

    def span(self, name: str, start_ns: int, end_ns: int, **fields):
        """Record one trace span for this request (no-op unless the
        engine sampled it). Spans land in the flight recorder ring and,
        through it, in the Profiler's Perfetto export; the tid keys
        each request onto its own trace row."""
        if not self.traced:
            return
        flight_recorder.record_span(
            f"req{self.id}.{name}", start_ns, end_ns,
            trace_id=self.trace_id, tid=1000 + self.id % 64,
            req=self.id, **fields)

    # ------------------------------------------------------------ handle
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until terminal. Without a background pump thread the
        calling thread drives the engine itself, so a synchronous
        ``submit(); result()`` makes progress instead of deadlocking."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while not self._event.is_set():
            pumped = self._engine._try_pump() \
                if self._engine is not None else False
            if not pumped:
                self._event.wait(0.005)
            if deadline is not None and time.monotonic() > deadline \
                    and not self._event.is_set():
                raise TimeoutError(
                    f"request {self.id} not finished within {timeout}s "
                    f"(status {self.status.value})")
        if self.status is RequestStatus.COMPLETED:
            return self.tokens
        raise RequestFailed(self.status, self.detail)

    # --------------------------------------------------------- scheduler
    def _finish(self, status: RequestStatus, detail: str = ""):
        """Terminal transition; idempotent (a drain racing a completion
        keeps the first outcome). Records the terminal event — and, for
        sampled requests, the final trace segment — into the flight
        recorder, so a dump taken moments later explains every request
        that just ended."""
        if self._event.is_set():
            return
        self.status = status
        self.detail = detail
        self.finished_at = time.monotonic()
        if flight_recorder.enabled:
            flight_recorder.record(
                "serve.finish", req=self.id, status=status.value,
                tokens=self.n_emitted,
                **({"detail": detail} if detail else {}))
            if self.traced:
                t = flight_recorder.now_ns()
                if self.admitted_at is None and self._t_submit_ns:
                    # never admitted: its whole life was queue wait
                    self.span("queue_wait", self._t_submit_ns, t,
                              status=status.value)
                elif self._t_seg_ns:
                    self.span("decode", self._t_seg_ns, t,
                              tokens=self.n_emitted, status=status.value)
        self._event.set()

    # ----------------------------------------------------------- timings
    @property
    def ttft(self) -> Optional[float]:
        """Submit -> first token (seconds) — includes queue wait."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def per_token_latency(self) -> Optional[float]:
        """Mean decode seconds/token after the first (None until
        terminal or when the request never decoded)."""
        if self.first_token_at is None or self.finished_at is None \
                or self.n_emitted <= 1:
            return None
        return (self.finished_at - self.first_token_at) / \
            (self.n_emitted - 1)

    def cost(self) -> dict:
        """Attributed resource cost so far: prefill wall seconds, this
        request's share of every decode poll window it was live in
        (window wall / live slots — the shares of one window sum to the
        window, so fleet-wide costs reconcile against the goodput
        ledger's compute bucket), and KV page*seconds held in the
        paged pool (0.0 on contiguous caches)."""
        return {
            "prefill_s": self._cost_prefill_s,
            "decode_s": self._cost_decode_s,
            "page_s": self._cost_page_s,
            "total_s": self._cost_prefill_s + self._cost_decode_s,
        }

    def __repr__(self):
        return (f"Request(id={self.id}, status={self.status.value}, "
                f"prompt={self.prompt.size} toks, budget={self.budget})")
