"""Viterbi decoding (≈ python/paddle/text/viterbi_decode.py over
phi/kernels/viterbi_decode_kernel.h) — the dynamic program is a
lax.scan over time, so the whole decode compiles to one XLA loop."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.op_registry import op

__all__ = ["viterbi_decode", "ViterbiDecoder"]


@op("viterbi_decode", differentiable=False)
def _viterbi_impl(potentials, transition, lengths, include_bos_eos_tag):
    """potentials [B, T, N], transition [N, N], lengths [B].
    Returns (scores [B], paths [B, T]).

    include_bos_eos_tag follows the reference convention
    (python/paddle/text/viterbi_decode.py): the LAST tag is BOS/start
    (its transition row scores BOS->tag) and the SECOND-TO-LAST tag is
    EOS/stop (its transition column scores tag->EOS) — both are part
    of the N tags."""
    B, T, N = potentials.shape
    if include_bos_eos_tag:
        start = transition[-1, :]   # BOS row
        stop = transition[:, -2]    # EOS column
    else:
        start = jnp.zeros((N,), potentials.dtype)
        stop = jnp.zeros((N,), potentials.dtype)
    trans = transition

    alpha0 = potentials[:, 0] + start[None, :]

    def step(alpha, t):
        emit = potentials[:, t]  # [B, N]
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        alpha2 = jnp.max(scores, axis=1) + emit
        # sequences shorter than t keep their old alpha
        active = (t < lengths)[:, None]
        alpha2 = jnp.where(active, alpha2, alpha)
        return alpha2, best_prev

    alpha_fin, backptrs = jax.lax.scan(
        step, alpha0, jnp.arange(1, T))  # backptrs [T-1, B, N]

    final = alpha_fin + stop[None, :]
    last_tag = jnp.argmax(final, axis=-1)  # [B]
    scores = jnp.max(final, axis=-1)

    def backtrack(carry, bp_t):
        # bp_t [B, N]; carry = (tag, t_index)
        tag, t = carry
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # positions beyond a sequence's length keep the same tag
        prev = jnp.where(t < lengths, prev, tag)
        return (prev, t - 1), tag

    (first_tag, _), rev_path = jax.lax.scan(
        backtrack, (last_tag.astype(jnp.int32), jnp.int32(T - 1)),
        backptrs, reverse=True)
    # rev_path [T-1, B] are tags at positions 1..T-1
    path = jnp.concatenate([first_tag[None, :], rev_path], axis=0)
    return scores, jnp.swapaxes(path, 0, 1)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag: bool = True):
    pot = potentials._data if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    if lengths is None:
        lengths = jnp.full((pot.shape[0],), pot.shape[1], jnp.int32)
    return _viterbi_impl(potentials, transition_params, lengths,
                         include_bos_eos_tag=include_bos_eos_tag)


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
