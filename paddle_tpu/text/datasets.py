"""paddle.text.datasets analog (python/paddle/text/datasets/ — Imdb,
UCIHousing, Conll05st, ...). Zero-egress environment: datasets read
standard local files; download=True raises with instructions."""
from __future__ import annotations

import os
import re
import tarfile
from typing import Callable, List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "UCIHousing", "FakeTextClassification",
           "Imikolov", "Conll05st", "Movielens", "WMT14", "WMT16"]


from ..io.dataset import no_download_gate as _no_download  # noqa: E402


class Imdb(Dataset):
    """IMDB sentiment from the standard aclImdb tar(.gz) archive or an
    extracted directory (pos/ and neg/ subdirs of train|test).
    `cutoff` is a MINIMUM WORD FREQUENCY — words appearing more than
    `cutoff` times enter the vocabulary (reference
    python/paddle/text/datasets/imdb.py semantics)."""

    def __init__(self, data_dir: Optional[str] = None,
                 mode: str = "train", cutoff: int = 150,
                 download: bool = False):
        if data_dir is None:
            _no_download(type(self).__name__)
        texts, labels = self._read_texts(data_dir, mode)
        self.docs: List[List[int]] = []
        self.labels: List[int] = []
        freq: dict = {}
        tokenized = [re.findall(r"[a-z']+", t) for t in texts]
        for toks in tokenized:
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
        # frequency threshold, most-frequent-first ids (reference order)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        for toks, label in zip(tokenized, labels):
            self.docs.append([self.word_idx.get(w, unk) for w in toks])
            self.labels.append(label)

    @staticmethod
    def _read_texts(data_dir: str, mode: str):
        texts, labels = [], []
        if os.path.isfile(data_dir):  # tar / tar.gz archive
            pat = re.compile(
                rf".*/{mode}/(pos|neg)/.*\.txt$")
            with tarfile.open(data_dir) as tf:
                for m in sorted(tf.getmembers(), key=lambda m: m.name):
                    g = pat.match(m.name)
                    if not g:
                        continue
                    texts.append(
                        tf.extractfile(m).read().decode("utf-8").lower())
                    labels.append(1 if g.group(1) == "pos" else 0)
            if not texts:
                raise FileNotFoundError(
                    f"no {mode}/pos|neg/*.txt members in {data_dir}")
            return texts, labels
        split_dir = os.path.join(data_dir, mode)
        if not os.path.isdir(split_dir):
            raise FileNotFoundError(f"{split_dir} not found")
        for label, sub in ((1, "pos"), (0, "neg")):
            d = os.path.join(split_dir, sub)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), encoding="utf-8") as f:
                    texts.append(f.read().lower())
                labels.append(label)
        return texts, labels

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return np.asarray(self.docs[idx], dtype=np.int64), \
            int(self.labels[idx])


class UCIHousing(Dataset):
    """Boston housing regression from the standard housing.data file
    (14 whitespace-separated columns)."""

    def __init__(self, data_file: Optional[str] = None,
                 mode: str = "train", download: bool = False):
        if data_file is None:
            _no_download(type(self).__name__)
        raw = np.loadtxt(data_file).astype(np.float32)
        # reference normalizes features then splits 80/20
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / np.maximum(feats.std(0), 1e-6)
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]


class FakeTextClassification(Dataset):
    """Synthetic token-sequence classification set for pipeline tests."""

    def __init__(self, size: int = 256, seq_len: int = 32,
                 vocab_size: int = 1000, num_classes: int = 2,
                 seed: int = 0):
        self.size, self.seq_len = size, seq_len
        self.vocab_size, self.num_classes = vocab_size, num_classes
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 7919 + idx)
        ids = rng.randint(0, self.vocab_size,
                          self.seq_len).astype(np.int64)
        return ids, int(rng.randint(self.num_classes))


class Imikolov(Dataset):
    """PTB language-model dataset from the standard simple-examples
    tgz (reference text/datasets/imikolov.py). data_type 'NGRAM'
    (sliding windows of window_size) or 'SEQ' (src/trg shifted pairs);
    vocab built from train+valid with min_word_freq, sorted by
    (-freq, word), '<unk>' last."""

    _TRAIN = "./simple-examples/data/ptb.train.txt"
    _VALID = "./simple-examples/data/ptb.valid.txt"

    def __init__(self, data_file: Optional[str] = None,
                 data_type: str = "NGRAM", window_size: int = -1,
                 mode: str = "train", min_word_freq: int = 50,
                 download: bool = False):
        data_type = data_type.upper()
        if data_type not in ("NGRAM", "SEQ"):
            raise AssertionError(
                f"data type should be 'NGRAM', 'SEQ', but got {data_type}")
        mode = mode.lower()
        if mode not in ("train", "test"):
            raise AssertionError(
                f"mode should be 'train', 'test', but got {mode}")
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_type, self.mode = data_type, mode
        self.window_size = window_size

        import collections
        freq: dict = collections.defaultdict(int)
        with tarfile.open(data_file) as tf:
            for member in (self._TRAIN, self._VALID):
                for line in tf.extractfile(member):
                    for w in line.strip().split():
                        freq[w.decode()] += 1
                    freq["<s>"] += 1
                    freq["<e>"] += 1
            freq.pop("<unk>", None)
            kept = sorted([kv for kv in freq.items()
                           if kv[1] > min_word_freq],
                          key=lambda kv: (-kv[1], kv[0]))
            words = [w for w, _ in kept]
            self.word_idx = {w: i for i, w in enumerate(words)}
            self.word_idx["<unk>"] = len(words)

            src = self._TRAIN if mode == "train" else \
                "./simple-examples/data/ptb.test.txt"
            self.data: List = []
            unk = self.word_idx["<unk>"]
            for line in tf.extractfile(src):
                toks = ["<s>"] + line.strip().decode().split() + ["<e>"]
                ids = [self.word_idx.get(w, unk) for w in toks]
                if data_type == "NGRAM":
                    if window_size <= 0:
                        raise AssertionError(
                            "window_size must be set for NGRAM data")
                    if len(ids) >= window_size:
                        for i in range(window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - window_size:i]))
                else:
                    self.data.append((ids[:-1], ids[1:]))

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(np.asarray(x) for x in row)

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL dataset (reference text/datasets/conll05.py):
    reads the standard conll05st-release tarball (test.wsj words/props
    gz members) plus word/verb/label dicts; items are the 9-tuple
    (word, ctx_n2..ctx_p2, pred, mark, label) index arrays."""

    def __init__(self, data_file: Optional[str] = None,
                 word_dict_file: Optional[str] = None,
                 verb_dict_file: Optional[str] = None,
                 target_dict_file: Optional[str] = None,
                 emb_file: Optional[str] = None, download: bool = False):
        if None in (data_file, word_dict_file, verb_dict_file,
                    target_dict_file):
            _no_download(type(self).__name__)
        self.word_dict = self._load_dict(word_dict_file)
        self.predicate_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self._emb_file = emb_file
        self._load_anno(data_file)

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path) as f:
            for i, line in enumerate(f):
                d[line.strip()] = i
        return d

    @staticmethod
    def _load_label_dict(path):
        """Expand the bracketed tag list into B-/I- variants + O
        (reference conll05.py:167)."""
        d = {}
        tag_dict = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("B-"):
                    tag_dict.add(line[2:])
                elif line.startswith("I-"):
                    tag_dict.add(line[2:])
        index = 0
        for tag in sorted(tag_dict):
            for prefix in ("B-", "I-"):
                d[prefix + tag] = index
                index += 1
        d["O"] = index
        return d

    def _load_anno(self, data_file):
        import gzip
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentences, labels, one_seg = [], [], []
                for word, label in zip(words_file, props_file):
                    word = word.strip().decode()
                    label = label.strip().decode().split()
                    if len(label) == 0:  # end of sentence
                        for i in range(len(one_seg[0]) if one_seg
                                       else 0):
                            labels.append([x[i] for x in one_seg])
                        if len(labels) >= 1:
                            verb_list = [x for x in labels[0]
                                         if x != "-"]
                            for i, lbl in enumerate(labels[1:]):
                                self.sentences.append(sentences)
                                self.predicates.append(verb_list[i])
                                self.labels.append(
                                    self._spans_to_bio(lbl))
                        sentences, labels, one_seg = [], [], []
                    else:
                        sentences.append(word)
                        one_seg.append(label)

    @staticmethod
    def _spans_to_bio(lbl):
        out, cur, inside = [], "O", False
        for l in lbl:
            if l == "*" and not inside:
                out.append("O")
            elif l == "*" and inside:
                out.append("I-" + cur)
            elif l == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in l and ")" in l:
                cur = l[1:l.find("*")]
                out.append("B-" + cur)
                inside = False
            elif "(" in l:
                cur = l[1:l.find("*")]
                out.append("B-" + cur)
                inside = True
            else:
                raise RuntimeError(f"Unexpected label: {l}")
        return out

    def __getitem__(self, idx):
        UNK_IDX = 0
        sentence, labels = self.sentences[idx], self.labels[idx]
        predicate = self.predicates[idx]
        sen_len = len(sentence)
        verb_index = labels.index("B-V")
        mark = [0] * len(labels)
        ctx = {}
        for off, name, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                               (0, "0", None), (1, "p1", "eos"),
                               (2, "p2", "eos")):
            j = verb_index + off
            if 0 <= j < len(labels):
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = pad
        word_idx = [self.word_dict.get(w, UNK_IDX) for w in sentence]
        rows = [np.array(word_idx)]
        for name in ("n2", "n1", "0", "p1", "p2"):
            rows.append(np.array(
                [self.word_dict.get(ctx[name], UNK_IDX)] * sen_len))
        rows.append(np.array(
            [self.predicate_dict.get(predicate)] * sen_len))
        rows.append(np.array(mark))
        rows.append(np.array([self.label_dict.get(w) for w in labels]))
        return tuple(rows)

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict


class Movielens(Dataset):
    """MovieLens ml-1m from the standard zip (reference
    text/datasets/movielens.py): items are user fields + movie fields
    + [[rating*2-5]] as arrays; train/test split by test_ratio with
    the global numpy RNG, matching the reference."""

    def __init__(self, data_file: Optional[str] = None,
                 mode: str = "train", test_ratio: float = 0.1,
                 rand_seed: int = 0, download: bool = False):
        import zipfile
        if data_file is None:
            _no_download(type(self).__name__)
        self.mode = mode.lower()
        np.random.seed(rand_seed)
        pat = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin").strip() \
                        .split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pat.match(title).group(1).strip()
                    self.movie_info[int(mid)] = (int(mid), cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            self.categories_dict = {c: i for i, c in
                                    enumerate(sorted(categories))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job = line.decode("latin") \
                        .strip().split("::")[:4]
                    self.user_info[int(uid)] = (
                        int(uid), 0 if gender == "M" else 1,
                        int(age), int(job))
            self.data = []
            is_test = self.mode == "test"
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (np.random.random() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode("latin") \
                        .strip().split("::")
                    uid, mid = int(uid), int(mid)
                    rating = float(rating) * 2 - 5.0
                    u = self.user_info[uid]
                    m = self.movie_info[mid]
                    self.data.append(
                        [[u[0]], [u[1]], [u[2]], [u[3]], [m[0]],
                         [self.categories_dict[c] for c in m[1]],
                         [self.movie_title_dict[w.lower()]
                          for w in m[2].split()],
                         [rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """WMT14 en->fr from the standard tarball layout (reference
    text/datasets/wmt14.py): *src.dict / *trg.dict members plus
    '{mode}/{mode}' tab-separated pair files; items are
    (src_ids, trg_ids, trg_ids_next)."""

    _START, _END, _UNK_IDX = "<s>", "<e>", 2

    def __init__(self, data_file: Optional[str] = None,
                 mode: str = "train", dict_size: int = -1,
                 download: bool = False):
        if data_file is None:
            _no_download(type(self).__name__)
        if mode.lower() not in ("train", "test", "gen"):
            raise AssertionError(
                f"mode should be 'train', 'test' or 'gen', got {mode}")
        if dict_size <= 0:
            raise AssertionError(
                "dict_size should be set as positive number")
        self.mode = mode.lower()
        self.dict_size = dict_size
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []

        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        with tarfile.open(data_file) as f:
            names = [m.name for m in f if m.name.endswith("src.dict")]
            self.src_dict = to_dict(f.extractfile(names[0]), dict_size)
            names = [m.name for m in f if m.name.endswith("trg.dict")]
            self.trg_dict = to_dict(f.extractfile(names[0]), dict_size)
            fname = f"{self.mode}/{self.mode}"
            for name in [m.name for m in f if m.name.endswith(fname)]:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, self._UNK_IDX)
                           for w in [self._START] + parts[0].split()
                           + [self._END]]
                    trg = [self.trg_dict.get(w, self._UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.trg_ids_next.append(
                        trg + [self.trg_dict[self._END]])
                    self.trg_ids.append(
                        [self.trg_dict[self._START]] + trg)
                    self.src_ids.append(src)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """WMT16 en<->de from the standard tarball (reference
    text/datasets/wmt16.py): 'wmt16/{train,val,test}' tab-separated
    files; vocab built from the train split per language with
    <pad>/<s>/<e>/<unk> specials; items are (src_ids, trg_ids,
    trg_ids_next)."""

    _SPECIALS = ["<pad>", "<s>", "<e>", "<unk>"]

    def __init__(self, data_file: Optional[str] = None,
                 mode: str = "train", src_dict_size: int = -1,
                 trg_dict_size: int = -1, lang: str = "en",
                 download: bool = False):
        if data_file is None:
            _no_download(type(self).__name__)
        if mode.lower() not in ("train", "test", "val"):
            raise AssertionError(
                f"mode should be 'train', 'test' or 'val', got {mode}")
        self.mode = mode.lower()
        self.lang = lang
        # single pass over wmt16/train counts both language columns
        import collections
        freqs = [collections.defaultdict(int),
                 collections.defaultdict(int)]
        with tarfile.open(data_file) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for col in (0, 1):
                    for w in parts[col].split():
                        freqs[col][w] += 1
        src_col = 0 if lang == "en" else 1
        self.src_dict = self._freq_to_dict(freqs[src_col],
                                           src_dict_size)
        self.trg_dict = self._freq_to_dict(freqs[1 - src_col],
                                           trg_dict_size)
        self._load(data_file)

    def _freq_to_dict(self, freq, dict_size):
        words = [w for w, _ in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))]
        if dict_size > 0:
            words = words[:max(dict_size - len(self._SPECIALS), 0)]
        vocab = self._SPECIALS + words
        return {w: i for i, w in enumerate(vocab)}

    def _load(self, data_file):
        bos, eos = self.src_dict["<s>"], self.src_dict["<e>"]
        unk_s, unk_t = self.src_dict["<unk>"], self.trg_dict["<unk>"]
        src_col = 0 if self.lang == "en" else 1
        member = {"train": "wmt16/train", "test": "wmt16/test",
                  "val": "wmt16/val"}[self.mode]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file) as f:
            for line in f.extractfile(member):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [bos] + [self.src_dict.get(w, unk_s)
                               for w in parts[src_col].split()] + [eos]
                trg_words = [self.trg_dict.get(w, unk_t)
                             for w in parts[1 - src_col].split()]
                self.src_ids.append(src)
                self.trg_ids.append(
                    [self.trg_dict["<s>"]] + trg_words)
                self.trg_ids_next.append(
                    trg_words + [self.trg_dict["<e>"]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d
