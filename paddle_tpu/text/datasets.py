"""paddle.text.datasets analog (python/paddle/text/datasets/ — Imdb,
UCIHousing, Conll05st, ...). Zero-egress environment: datasets read
standard local files; download=True raises with instructions."""
from __future__ import annotations

import os
import re
import tarfile
from typing import Callable, List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "UCIHousing", "FakeTextClassification"]


def _no_download(name: str):
    raise RuntimeError(
        f"{name}: download is unavailable in this environment; place "
        f"the standard files locally and pass data_file/data_dir")


class Imdb(Dataset):
    """IMDB sentiment from the standard aclImdb tar(.gz) archive or an
    extracted directory (pos/ and neg/ subdirs of train|test).
    `cutoff` is a MINIMUM WORD FREQUENCY — words appearing more than
    `cutoff` times enter the vocabulary (reference
    python/paddle/text/datasets/imdb.py semantics)."""

    def __init__(self, data_dir: Optional[str] = None,
                 mode: str = "train", cutoff: int = 150,
                 download: bool = False):
        if data_dir is None:
            _no_download(type(self).__name__)
        texts, labels = self._read_texts(data_dir, mode)
        self.docs: List[List[int]] = []
        self.labels: List[int] = []
        freq: dict = {}
        tokenized = [re.findall(r"[a-z']+", t) for t in texts]
        for toks in tokenized:
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
        # frequency threshold, most-frequent-first ids (reference order)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        for toks, label in zip(tokenized, labels):
            self.docs.append([self.word_idx.get(w, unk) for w in toks])
            self.labels.append(label)

    @staticmethod
    def _read_texts(data_dir: str, mode: str):
        texts, labels = [], []
        if os.path.isfile(data_dir):  # tar / tar.gz archive
            pat = re.compile(
                rf".*/{mode}/(pos|neg)/.*\.txt$")
            with tarfile.open(data_dir) as tf:
                for m in sorted(tf.getmembers(), key=lambda m: m.name):
                    g = pat.match(m.name)
                    if not g:
                        continue
                    texts.append(
                        tf.extractfile(m).read().decode("utf-8").lower())
                    labels.append(1 if g.group(1) == "pos" else 0)
            if not texts:
                raise FileNotFoundError(
                    f"no {mode}/pos|neg/*.txt members in {data_dir}")
            return texts, labels
        split_dir = os.path.join(data_dir, mode)
        if not os.path.isdir(split_dir):
            raise FileNotFoundError(f"{split_dir} not found")
        for label, sub in ((1, "pos"), (0, "neg")):
            d = os.path.join(split_dir, sub)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), encoding="utf-8") as f:
                    texts.append(f.read().lower())
                labels.append(label)
        return texts, labels

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return np.asarray(self.docs[idx], dtype=np.int64), \
            int(self.labels[idx])


class UCIHousing(Dataset):
    """Boston housing regression from the standard housing.data file
    (14 whitespace-separated columns)."""

    def __init__(self, data_file: Optional[str] = None,
                 mode: str = "train", download: bool = False):
        if data_file is None:
            _no_download(type(self).__name__)
        raw = np.loadtxt(data_file).astype(np.float32)
        # reference normalizes features then splits 80/20
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / np.maximum(feats.std(0), 1e-6)
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]


class FakeTextClassification(Dataset):
    """Synthetic token-sequence classification set for pipeline tests."""

    def __init__(self, size: int = 256, seq_len: int = 32,
                 vocab_size: int = 1000, num_classes: int = 2,
                 seed: int = 0):
        self.size, self.seq_len = size, seq_len
        self.vocab_size, self.num_classes = vocab_size, num_classes
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 7919 + idx)
        ids = rng.randint(0, self.vocab_size,
                          self.seq_len).astype(np.int64)
        return ids, int(rng.randint(self.num_classes))
