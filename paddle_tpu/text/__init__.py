"""paddle.text analog (python/paddle/text/) — NLP datasets +
viterbi_decode/ViterbiDecoder."""
from . import datasets  # noqa: F401
from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401
