"""paddle.text analog (python/paddle/text/) — NLP datasets +
viterbi_decode/ViterbiDecoder."""
from . import datasets  # noqa: F401
from .datasets import (Conll05st, Imdb, Imikolov,  # noqa: F401
                       Movielens, UCIHousing, WMT14, WMT16)
from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401
