"""Profiler subsystem.

Reference analog: python/paddle/profiler/profiler.py (Profiler with
scheduler states :74, export_chrome_tracing :210, RecordEvent
instrumentation) over the C++ HostTracer/CudaTracer pair
(paddle/fluid/platform/profiler/). Here:

- host spans come from the native C++ lock-free recorder
  (paddle_tpu/native/host_tracer.cc) with a pure-Python fallback;
- device traces come from jax.profiler (XPlane → TensorBoard/Perfetto),
  started/stopped by the same scheduler states;
- op-level spans are emitted by core.tensor.dispatch through prof_hook
  when a Profiler is recording (the reference hooks RecordEvent into its
  executors the same way).

Usage (reference API shape):

    import paddle_tpu.profiler as profiler
    p = profiler.Profiler(
        targets=[profiler.ProfilerTarget.CPU],
        scheduler=profiler.make_scheduler(closed=1, ready=1, record=3),
        on_trace_ready=profiler.export_chrome_tracing('./log'))
    p.start()
    for it, batch in enumerate(loader()):
        train_step(batch)
        p.step()
    p.stop()
    p.summary()
"""
from . import metrics  # noqa: F401
from .profiler import (Profiler, ProfilerResult, ProfilerState,  # noqa: F401
                       ProfilerTarget, RecordEvent, SummaryView,
                       export_chrome_tracing, export_protobuf,
                       load_profiler_result, make_scheduler)
from .statistic import (SortedKeys, summary_report,  # noqa: F401
                        summary_table, view_table)
