"""User-facing address of the runtime metrics registry.

The implementation lives in core.metrics (below every instrumented
layer, imports nothing from paddle_tpu); this module is the same
registry re-exported where users expect it, next to Profiler:

    from paddle_tpu.profiler import metrics
    metrics.enable()
    metrics.counter("my.counter").inc()
    print(metrics.report())
"""
from ..core.metrics import (Counter, Gauge, Histogram,  # noqa: F401
                            counter, disable, enable, gauge, histogram,
                            is_enabled, is_sampling, on_state_change,
                            report, reset, snapshot, start_sampling,
                            stop_sampling)
