"""Event aggregation + summary tables (≈ profiler_statistic.py's
summary views: OverView/OperatorView/MemoryView/DistributedView built
from host spans + the runtime metrics registry)."""
from __future__ import annotations

import enum
import re
from collections import defaultdict
from typing import List, Optional


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    Calls = 3


_UNIT = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


def aggregate(events: List[tuple]):
    """events: [(name, start_ns, end_ns, tid, mem)] ->
    {name: dict(calls, total_ns, avg_ns, min_ns, max_ns)}"""
    stats = defaultdict(lambda: {"calls": 0, "total_ns": 0,
                                 "min_ns": None, "max_ns": 0})
    for name, start, end, _tid, _mem in events:
        dur = max(end - start, 0)
        s = stats[name]
        s["calls"] += 1
        s["total_ns"] += dur
        s["max_ns"] = max(s["max_ns"], dur)
        s["min_ns"] = dur if s["min_ns"] is None else min(s["min_ns"], dur)
    for s in stats.values():
        s["avg_ns"] = s["total_ns"] / max(s["calls"], 1)
        s["min_ns"] = s["min_ns"] or 0
    return dict(stats)


def summary_table(events: List[tuple],
                  sorted_by: Optional[SortedKeys] = None,
                  time_unit: str = "ms") -> str:
    stats = aggregate(events)
    div = _UNIT[time_unit]
    key = {
        None: lambda kv: -kv[1]["total_ns"],
        SortedKeys.CPUTotal: lambda kv: -kv[1]["total_ns"],
        SortedKeys.CPUAvg: lambda kv: -kv[1]["avg_ns"],
        SortedKeys.CPUMax: lambda kv: -kv[1]["max_ns"],
        SortedKeys.Calls: lambda kv: -kv[1]["calls"],
    }[sorted_by]
    rows = sorted(stats.items(), key=key)
    name_w = max([len(n) for n, _ in rows] + [8])
    header = (f"{'Name':<{name_w}}  {'Calls':>7}  "
              f"{'Total(' + time_unit + ')':>12}  "
              f"{'Avg(' + time_unit + ')':>12}  "
              f"{'Max(' + time_unit + ')':>12}")
    lines = [header, "-" * len(header)]
    for name, s in rows:
        lines.append(
            f"{name:<{name_w}}  {s['calls']:>7}  "
            f"{s['total_ns'] / div:>12.4f}  {s['avg_ns'] / div:>12.4f}  "
            f"{s['max_ns'] / div:>12.4f}")
    return "\n".join(lines)


# ----------------------------------------------------------- view tables
# Each view renders a titled table from (host spans, metrics snapshot);
# the reference builds the same views from its C++ event/stat collectors
# (profiler_statistic.py OperatorSummary/MemorySummary/DistributedSummary).

def _table(title: str, columns, rows) -> str:
    widths = [max(len(str(c)), *(len(str(r[i])) for r in rows))
              if rows else len(str(c)) for i, c in enumerate(columns)]
    head = "  ".join(f"{c:<{w}}" for c, w in zip(columns, widths))
    lines = [f"---- {title} ----", head, "-" * len(head)]
    for r in rows:
        lines.append("  ".join(f"{str(v):<{w}}"
                               for v, w in zip(r, widths)))
    if not rows:
        lines.append("(no data recorded)")
    return "\n".join(lines)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


_LABELED = re.compile(r"^(?P<base>[^{]+)\{(?P<labels>[^}]*)\}$")


def _split_metric(name: str):
    """'comm.bytes{axis=dp,op=all_reduce}' -> ('comm.bytes',
    {'axis': 'dp', 'op': 'all_reduce'})"""
    m = _LABELED.match(name)
    if not m:
        return name, {}
    labels = dict(kv.split("=", 1) for kv in
                  m.group("labels").split(",") if "=" in kv)
    return m.group("base"), labels


def overview(events, snapshot, time_unit: str = "ms") -> str:
    div = _UNIT[time_unit]
    span_ns = sum(max(e - s, 0) for _, s, e, _, _ in events)
    tids = {t for _, _, _, t, _ in events}
    rows = [
        ("host spans", len(events)),
        (f"host span time ({time_unit})", f"{span_ns / div:.4f}"),
        ("threads", len(tids)),
    ]
    for key, label in (("jit.compile.total", "jit compiles/retraces"),
                       ("static.ops_recorded", "static ops recorded"),
                       ("io.batches", "dataloader batches"),
                       ("amp.scaler.skipped", "amp skipped steps")):
        d = snapshot.get(key)
        if d:
            rows.append((label, d["value"]))
    return _table("OverView", ("Metric", "Value"), rows)


def operator_view(events, snapshot=None, time_unit: str = "ms") -> str:
    ops = [e for e in events if e[0].startswith("op::")]
    body = summary_table(ops, time_unit=time_unit) if ops \
        else "(no op spans recorded)"
    return f"---- OperatorView ----\n{body}"


def memory_view(events, snapshot, time_unit: str = "ms") -> str:
    rows = []
    for name in sorted(snapshot):
        d = snapshot[name]
        base, _ = _split_metric(name)
        if d["kind"] == "gauge" and ("memory" in base or
                                     base.endswith(".bytes_in_use")):
            rows.append((name, _fmt_bytes(d["value"]),
                         _fmt_bytes(d["peak"])))
    # host spans that carried allocation payloads (native tracer mem col)
    mem_spans = defaultdict(int)
    for name, _s, _e, _t, mem in events:
        if mem:
            mem_spans[name] += mem
    for name, total in sorted(mem_spans.items(), key=lambda kv: -kv[1]):
        rows.append((f"span:{name}", _fmt_bytes(total), ""))
    return _table("MemoryView", ("Name", "Current", "Peak"), rows)


def distributed_view(events, snapshot, time_unit: str = "ms") -> str:
    # {(axis, op): [calls, bytes]}
    per = defaultdict(lambda: [0, 0])
    for name, d in snapshot.items():
        base, labels = _split_metric(name)
        if "op" not in labels:
            continue
        key = (labels.get("axis", "?"), labels["op"])
        if base == "comm.ops":
            per[key][0] += d["value"]
        elif base == "comm.bytes":
            per[key][1] += d["value"]
    rows = [(axis, op, calls, _fmt_bytes(nbytes))
            for (axis, op), (calls, nbytes) in
            sorted(per.items(), key=lambda kv: -kv[1][1])]
    return _table("DistributedView", ("Axis", "Op", "Calls", "Bytes"),
                  rows)


_VIEWS = {
    "OverView": overview,
    "OperatorView": operator_view,
    "MemoryView": memory_view,
    "DistributedView": distributed_view,
}


def view_table(view_name: str, events, snapshot,
               time_unit: str = "ms") -> str:
    """Render one SummaryView table by enum name; unknown/legacy views
    (DeviceView, KernelView, ...) fall back to the flat span table."""
    fn = _VIEWS.get(view_name)
    if fn is None:
        return summary_table(events, time_unit=time_unit)
    return fn(events, snapshot, time_unit=time_unit)


def summary_report(events, snapshot, time_unit: str = "ms") -> str:
    """The flat span table plus all four views stacked — what
    Profiler.summary() prints when no specific view is requested and
    metrics were recorded alongside the spans."""
    sections = [summary_table(events, time_unit=time_unit)]
    sections += [fn(events, snapshot, time_unit=time_unit)
                 for fn in (overview, operator_view, memory_view,
                            distributed_view)]
    return "\n\n".join(sections)
