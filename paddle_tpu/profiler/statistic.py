"""Event aggregation + summary table (≈ profiler_statistic.py's
kernel/op summary views)."""
from __future__ import annotations

import enum
from collections import defaultdict
from typing import List, Optional


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    Calls = 3


_UNIT = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


def aggregate(events: List[tuple]):
    """events: [(name, start_ns, end_ns, tid, mem)] ->
    {name: dict(calls, total_ns, avg_ns, min_ns, max_ns)}"""
    stats = defaultdict(lambda: {"calls": 0, "total_ns": 0,
                                 "min_ns": None, "max_ns": 0})
    for name, start, end, _tid, _mem in events:
        dur = max(end - start, 0)
        s = stats[name]
        s["calls"] += 1
        s["total_ns"] += dur
        s["max_ns"] = max(s["max_ns"], dur)
        s["min_ns"] = dur if s["min_ns"] is None else min(s["min_ns"], dur)
    for s in stats.values():
        s["avg_ns"] = s["total_ns"] / max(s["calls"], 1)
        s["min_ns"] = s["min_ns"] or 0
    return dict(stats)


def summary_table(events: List[tuple],
                  sorted_by: Optional[SortedKeys] = None,
                  time_unit: str = "ms") -> str:
    stats = aggregate(events)
    div = _UNIT[time_unit]
    key = {
        None: lambda kv: -kv[1]["total_ns"],
        SortedKeys.CPUTotal: lambda kv: -kv[1]["total_ns"],
        SortedKeys.CPUAvg: lambda kv: -kv[1]["avg_ns"],
        SortedKeys.CPUMax: lambda kv: -kv[1]["max_ns"],
        SortedKeys.Calls: lambda kv: -kv[1]["calls"],
    }[sorted_by]
    rows = sorted(stats.items(), key=key)
    name_w = max([len(n) for n, _ in rows] + [8])
    header = (f"{'Name':<{name_w}}  {'Calls':>7}  "
              f"{'Total(' + time_unit + ')':>12}  "
              f"{'Avg(' + time_unit + ')':>12}  "
              f"{'Max(' + time_unit + ')':>12}")
    lines = [header, "-" * len(header)]
    for name, s in rows:
        lines.append(
            f"{name:<{name_w}}  {s['calls']:>7}  "
            f"{s['total_ns'] / div:>12.4f}  {s['avg_ns'] / div:>12.4f}  "
            f"{s['max_ns'] / div:>12.4f}")
    return "\n".join(lines)
