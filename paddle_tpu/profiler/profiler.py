"""Profiler core: scheduler states, RecordEvent, host+device capture."""
from __future__ import annotations

import enum
import json
import os
import threading
import time
from typing import Callable, Iterable, List, Optional

from ..core import prof_hook
from . import metrics


class ProfilerState(enum.Enum):
    """≈ python/paddle/profiler/profiler.py:74 ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3   # last record step of a cycle: trace is handed
    # to on_trace_ready


class ProfilerTarget(enum.Enum):
    CPU = 0   # host spans (native tracer)
    TPU = 1   # jax.profiler device trace (XPlane)


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """Step-number -> ProfilerState cycle (≈ profiler.py make_scheduler):
    skip_first CLOSED steps once, then cycles of [closed, ready, record]
    with the last record step RECORD_AND_RETURN; repeat=0 cycles forever."""
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("closed/ready must be >=0 and record >= 1")
    span = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        cycle = step // span
        if repeat > 0 and cycle >= repeat:
            return ProfilerState.CLOSED
        pos = step % span
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == span - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


# ------------------------------------------------------------ host events

class _PyRecorder:
    """Pure-Python fallback for the native host tracer."""

    def __init__(self):
        self.events: List[tuple] = []
        self._stack = threading.local()
        self.enabled = False

    def begin(self, name: str):
        if not self.enabled:
            return
        stack = getattr(self._stack, "s", None)
        if stack is None:
            stack = self._stack.s = []
        stack.append((name, time.perf_counter_ns()))

    def end(self):
        if not self.enabled:
            return
        stack = getattr(self._stack, "s", None)
        if stack:
            name, start = stack.pop()
            self.events.append(
                (name, start, time.perf_counter_ns(),
                 threading.get_ident() % 100000, 0))

    def collect(self):
        out, self.events = self.events, []
        return out


_py_recorder = _PyRecorder()


def _native_lib():
    from .. import native
    return native.lib()


class RecordEvent:
    """User-facing span (≈ paddle.profiler.RecordEvent): context manager
    and decorator. Events only record while a Profiler is in a RECORD
    state (or after RecordEvent.begin() when used manually)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        lib = _native_lib()
        if lib is not None:
            lib.pt_record_begin(self.name.encode())
        else:
            _py_recorder.begin(self.name)

    def end(self):
        lib = _native_lib()
        if lib is not None:
            lib.pt_record_end()
        else:
            _py_recorder.end()

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)

        return wrapped


def _host_enable():
    lib = _native_lib()
    if lib is not None:
        lib.pt_tracer_enable()
        prof_hook.enable(lib.pt_record_begin,
                         lib.pt_record_end)
    else:
        _py_recorder.enabled = True
        prof_hook.enable(
            lambda name: _py_recorder.begin(name.decode()),
            _py_recorder.end)


def _host_disable():
    lib = _native_lib()
    if lib is not None:
        lib.pt_tracer_disable()
    else:
        _py_recorder.enabled = False
    prof_hook.disable()


def _host_collect() -> List[tuple]:
    """[(name, start_ns, end_ns, tid, mem_bytes)]"""
    lib = _native_lib()
    if lib is None:
        return _py_recorder.collect()
    import ctypes
    from .. import native
    evp = ctypes.POINTER(native.CollectedEvent)()
    cnt = ctypes.c_uint64()
    arena = lib.pt_collect(ctypes.byref(evp), ctypes.byref(cnt))
    out = [(evp[i].name.decode(), evp[i].start_ns, evp[i].end_ns,
            evp[i].tid, evp[i].mem_bytes) for i in range(cnt.value)]
    lib.pt_free_events(arena)
    return out


# ---------------------------------------------------------------- results

class ProfilerResult:
    def __init__(self, events: List[tuple], device_trace_dir: Optional[str],
                 counter_samples: Optional[dict] = None,
                 metrics_snapshot: Optional[dict] = None):
        #: [(name, start_ns, end_ns, tid, mem_bytes)]
        self.events = events
        #: directory holding the jax/XPlane device trace, if captured
        self.device_trace_dir = device_trace_dir
        #: {metric_name: [(perf_counter_ns, value)]} captured while
        #: recording — becomes "ph": "C" counter tracks in the trace
        self.counter_samples = counter_samples or {}
        #: metrics registry snapshot at end-of-record — feeds the
        #: Memory/Distributed summary views
        self.metrics_snapshot = metrics_snapshot or {}

    def export_chrome_tracing(self, path: str):
        """Write a chrome://tracing / Perfetto JSON: "ph": "X" span
        events for host spans plus "ph": "C" counter events for every
        sampled metric (memory, collective bytes, ...), all under this
        process's real pid so merged multi-host traces stay
        distinguishable (≈ chrometracing_logger.cc output)."""
        pid = os.getpid()
        trace_events = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"host_{pid}"}}]
        trace_events += [
            {"name": name, "ph": "X", "cat": "host",
             "ts": start / 1000.0, "dur": max(end - start, 0) / 1000.0,
             "pid": pid, "tid": tid,
             **({"args": {"bytes": mem}} if mem else {})}
            for name, start, end, tid, mem in self.events]
        for metric, samples in self.counter_samples.items():
            trace_events += [
                {"name": metric, "ph": "C", "cat": "metric",
                 "ts": ts / 1000.0, "pid": pid,
                 "args": {metric: value}}
                for ts, value in samples]
        trace = {"traceEvents": trace_events}
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return path

    def summary(self, sorted_by=None, time_unit: str = "ms") -> str:
        from . import statistic
        if isinstance(sorted_by, SummaryView):
            return statistic.view_table(
                sorted_by.name, self.events, self.metrics_snapshot,
                time_unit=time_unit)
        if sorted_by is None and self.metrics_snapshot:
            return statistic.summary_report(
                self.events, self.metrics_snapshot, time_unit=time_unit)
        return statistic.summary_table(self.events, sorted_by=sorted_by,
                                       time_unit=time_unit)


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready factory (≈ profiler.py:210): writes
    {dir}/{worker}_{cycle}.json per completed record cycle."""

    def handler(prof: "Profiler"):
        result = prof.result
        if result is None:
            return
        worker = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{worker}_{prof._cycle}.json")
        result.export_chrome_tracing(path)

    return handler


# --------------------------------------------------------------- profiler

class Profiler:
    """Scheduler-driven profiler combining the native host tracer with
    jax.profiler device capture (≈ paddle.profiler.Profiler)."""

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler: Optional[Callable] = None,
                 on_trace_ready: Optional[Callable] = None,
                 trace_dir: Optional[str] = None,
                 timer_only: bool = False):
        self.targets = list(targets) if targets is not None else \
            [ProfilerTarget.CPU]
        if callable(scheduler):
            self.scheduler = scheduler
        elif scheduler is None:
            self.scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            if not all(isinstance(v, int) for v in (start, end)) \
                    or start < 0 or end <= start:
                raise ValueError(
                    f"scheduler={tuple(scheduler)!r}: a (start, end) "
                    f"tuple needs integers with 0 <= start < end "
                    f"(records steps [start, end))")
            self.scheduler = make_scheduler(
                closed=start, ready=0, record=end - start, repeat=1)
        else:
            raise TypeError(f"bad scheduler {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir or os.path.join(
            os.getcwd(), "profiler_log")
        self.result: Optional[ProfilerResult] = None
        self.current_state = ProfilerState.CLOSED
        self._step = 0
        self._cycle = 0
        self._device_tracing = False
        self._started = False
        self._pending_events: List[tuple] = []  # drained mid-cycle by
        # summary(); folded into the next _finish_record

    # -- lifecycle
    def start(self):
        self._started = True
        self._transition(self.scheduler(self._step))

    def stop(self):
        if not self._started:
            return
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._finish_record()
        self._started = False
        self.current_state = ProfilerState.CLOSED

    def step(self):
        """Advance one iteration; drives the state machine. While
        recording, each step boundary also polls device memory into the
        metrics gauges so the trace gets a per-step memory track."""
        if not self._started:
            return
        if not self.timer_only and \
                self.current_state in (ProfilerState.RECORD,
                                       ProfilerState.RECORD_AND_RETURN):
            from ..core import monitor
            monitor.sample_device_memory()
        self._step += 1
        self._transition(self.scheduler(self._step))

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- state machine
    def _transition(self, new: ProfilerState):
        """Called at each step boundary with the next step's state. A
        RECORD_AND_RETURN step flushes when we LEAVE it (its work has
        run by then); leaving RECORD for a non-recording state flushes
        too."""
        old = self.current_state
        rec_old = old in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN)
        rec_new = new in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN)
        if rec_old and (old is ProfilerState.RECORD_AND_RETURN
                        or not rec_new):
            self._finish_record()
            rec_old = False
        if not rec_old and rec_new:
            self._begin_record()
        self.current_state = new

    def _begin_record(self):
        if not self.timer_only:
            _host_enable()
        # drive the metrics registry for the duration of the record
        # window (leave it alone if the user enabled it themselves);
        # timer_only keeps its minimal-overhead contract: no registry,
        # no sampling, no memory polling
        self._metrics_were_enabled = metrics.is_enabled()
        if not self.timer_only:
            metrics.enable()
            metrics.start_sampling()
            from ..core import monitor
            monitor.sample_device_memory()
        if ProfilerTarget.TPU in self.targets and not self.timer_only:
            try:
                import jax
                os.makedirs(self.trace_dir, exist_ok=True)
                jax.profiler.start_trace(self.trace_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False
        self._record_t0 = time.perf_counter()

    def _finish_record(self):
        device_dir = None
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
                device_dir = self.trace_dir
            except Exception:
                pass
            self._device_tracing = False
        if not self.timer_only:
            _host_disable()
            events = self._pending_events + _host_collect()
            self._pending_events = []
            # sampled serving-request spans (flight recorder) that
            # completed inside the record window join the same trace:
            # queue-wait/prefill/decode segments render as "ph": "X"
            # slices next to RecordEvent spans and counter tracks
            from ..core import flight_recorder
            t0_ns = int(getattr(self, "_record_t0", 0) * 1e9)
            events += flight_recorder.spans_between(
                t0_ns, time.perf_counter_ns())
        else:
            events = []
        if not self.timer_only:
            from ..core import monitor
            monitor.sample_device_memory()
            snapshot = metrics.snapshot()
            counter_samples = metrics.stop_sampling()
            if not getattr(self, "_metrics_were_enabled", False):
                metrics.disable()
        else:
            snapshot, counter_samples = None, None
        self.result = ProfilerResult(events, device_dir,
                                     counter_samples, snapshot)
        self._cycle += 1
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def summary(self, sorted_by=None, time_unit: str = "ms"):
        """Print the aggregated span table. Read-only with respect to the
        cycle state machine: calling it mid-recording peeks at the events
        recorded so far (they still appear in the final trace) and does
        NOT fire on_trace_ready or advance the cycle counter."""
        result = self.result
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN) \
                and not self.timer_only:
            self._pending_events += _host_collect()
            result = ProfilerResult(list(self._pending_events), None,
                                    None, metrics.snapshot())
        if result is None:
            print("No profiler data recorded.")
            return
        print(result.summary(sorted_by=sorted_by, time_unit=time_unit))


class SummaryView(enum.Enum):
    """Which table summary() prints (reference profiler/profiler.py
    SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(result: "ProfilerResult", path: str):
    """Persist a ProfilerResult (reference export_protobuf writes the
    profiler protobuf dump; here a self-contained pickle of the host
    spans + device-trace pointer — load_profiler_result reads it)."""
    import pickle
    with open(path, "wb") as f:
        pickle.dump({"events": result.events,
                     "device_trace_dir": result.device_trace_dir,
                     "counter_samples": result.counter_samples,
                     "metrics_snapshot": result.metrics_snapshot}, f)


def load_profiler_result(path: str) -> "ProfilerResult":
    """Reload a dump written by export_protobuf (reference
    load_profiler_result)."""
    import pickle
    with open(path, "rb") as f:
        d = pickle.load(f)
    return ProfilerResult(d["events"], d.get("device_trace_dir"),
                          d.get("counter_samples"),
                          d.get("metrics_snapshot"))
