"""Paths for building extensions against the framework (reference:
python/paddle/sysconfig.py:20,38 — get_include/get_lib point at the
shipped headers and libpaddle; here they point at the package and its
native/ directory, which is what utils.cpp_extension compiles against).
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing the C headers for custom-op/native builds
    (the C ABI consumed by utils.cpp_extension lives in native/)."""
    return os.path.join(_PKG_DIR, "native")


def get_lib() -> str:
    """Directory containing compiled native libraries (populated by the
    lazy builds in paddle_tpu.native / utils.cpp_extension)."""
    return os.path.join(_PKG_DIR, "native")
