"""The framework lint rules (see package docstring for the contract).

Each rule is a function ``(FileContext) -> [LintFinding]`` registered
under its id; ids double as the allowlist-marker names
(``# lint: host-sync-ok``). Rules use only stdlib ``ast`` — the lint
must run in any environment, including ones where jax cannot import.
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from . import FileContext, LintFinding, rule

# ---------------------------------------------------------------- config

# Modules on the per-step hot path: one stray eager host read here is a
# pipeline stall under traffic. Anything else may sync freely.
HOST_SYNC_HOT_PATHS = frozenset({
    "paddle_tpu/jit/api.py",
    "paddle_tpu/distributed/fleet/train_step.py",
    "paddle_tpu/io/device_prefetch.py",
    "paddle_tpu/generation/api.py",
    "paddle_tpu/generation/kv_cache.py",
    "paddle_tpu/generation/paged_cache.py",
    "paddle_tpu/generation/attention.py",
    "paddle_tpu/generation/speculative.py",
    "paddle_tpu/hapi/model.py",
    "paddle_tpu/serving/engine.py",
    "paddle_tpu/serving/router.py",
})

# Files allowed to name metrics freely (the schema itself + the
# registry implementation and its re-export).
METRIC_NAME_EXEMPT = frozenset({
    "paddle_tpu/core/monitor.py",
    "paddle_tpu/core/metrics.py",
    "paddle_tpu/profiler/metrics.py",
})

_FAULT_INJECTION_MODULE = "paddle_tpu.utils.fault_injection"


def _dotted(node: ast.AST) -> str:
    """'np.random.randn' for an Attribute/Name chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ------------------------------------------------------------- host-sync

@rule("host-sync")
def check_host_sync(ctx: FileContext) -> List[LintFinding]:
    """Eager device->host reads in hot-path modules: ``.numpy()``,
    ``.item()``, ``float(tensor)``, ``np.asarray(tensor)``, and
    ``bool(<call>)`` (the ``bool(jnp.all(done))`` polling spelling)
    each block the dispatch queue. Deliberate sync points (the async
    loop's bounded loss fetch, generate()'s end-of-call transfer, the
    every-K-steps eos poll) carry ``# lint: host-sync-ok`` with a
    reason. Known limitation: ``bool(x)``/``int(x)`` on a BARE name
    can't be told apart from config coercion without type info, so
    only call/attribute arguments are flagged — reviewers should still
    eyeball truthiness tests of device arrays."""
    if ctx.relpath not in HOST_SYNC_HOT_PATHS:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        label = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("numpy", "item") and not node.args:
            label = f".{node.func.attr}()"
        elif isinstance(node.func, ast.Name) and node.func.id == "float" \
                and len(node.args) == 1 \
                and not isinstance(node.args[0], ast.Constant):
            label = "float(...)"
        elif isinstance(node.func, ast.Name) and node.func.id == "bool" \
                and len(node.args) == 1 \
                and isinstance(node.args[0], (ast.Call, ast.Attribute)):
            label = "bool(...)"
        elif _dotted(node.func) in ("np.asarray", "numpy.asarray"):
            label = "np.asarray(...)"
        if label is None or ctx.allowed(node, "host-sync"):
            continue
        findings.append(LintFinding(
            ctx.relpath, node.lineno, node.col_offset, "host-sync",
            f"{label} in a hot-path module forces a host sync; move it "
            "off the per-step path or mark the line "
            "'# lint: host-sync-ok (reason)' if it is a deliberate "
            "sync point"))
    return findings


# ------------------------------------------------------------ jit-random

def _jitted_function_names(tree: ast.Module) -> Set[str]:
    """Names of functions that get jitted in this module: decorated
    with jit/to_static (any dotted spelling), or passed by name to a
    ``jax.jit(...)`` / ``jit(...)`` / ``to_static(...)`` call."""
    jit_entries = {"jit", "to_static"}
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = _dotted(target)
                if dotted.split(".")[-1] in jit_entries:
                    names.add(node.name)
                # functools.partial(jax.jit, ...) decorators
                if isinstance(dec, ast.Call) and dec.args and \
                        _dotted(dec.args[0]).split(".")[-1] in jit_entries:
                    names.add(node.name)
        elif isinstance(node, ast.Call):
            if _dotted(node.func).split(".")[-1] in jit_entries and \
                    node.args and isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
    return names


@rule("jit-random")
def check_jit_randomness(ctx: FileContext) -> List[LintFinding]:
    """``np.random.*`` / stdlib ``random.*`` inside a function that
    gets jitted: the draw happens ONCE at trace time and is baked into
    the program as a constant — every execution replays it. Use
    ``jax.random`` with an explicit key (or draw outside the jitted
    function and pass the result in)."""
    jitted = _jitted_function_names(ctx.tree)
    if not jitted:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or node.name not in jitted:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func)
            if not (dotted.startswith("np.random.")
                    or dotted.startswith("numpy.random.")
                    or dotted.startswith("random.")):
                continue
            if ctx.allowed(sub, "jit-random"):
                continue
            findings.append(LintFinding(
                ctx.relpath, sub.lineno, sub.col_offset, "jit-random",
                f"{dotted}() inside jitted function "
                f"'{node.name}' is drawn once at trace time and baked "
                "into the program; use jax.random with an explicit "
                "key"))
    return findings


# ----------------------------------------------------------- bare-except

@rule("bare-except")
def check_bare_except(ctx: FileContext) -> List[LintFinding]:
    """``except:`` that neither re-raises nor records through
    ``monitor.record_swallowed``: a silently swallowed error is how
    fault-tolerance bugs hide (PR 3 added the recorder precisely so
    deliberate swallows stay observable)."""
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler) or node.type is not None:
            continue
        ok = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                ok = True
            elif isinstance(sub, ast.Call) and \
                    _dotted(sub.func).endswith("record_swallowed"):
                ok = True
        if ok or ctx.allowed(node, "bare-except"):
            continue
        findings.append(LintFinding(
            ctx.relpath, node.lineno, node.col_offset, "bare-except",
            "bare 'except:' without re-raise or "
            "monitor.record_swallowed(...): swallow observably (catch "
            "a concrete exception type, or record the swallow)"))
    return findings


# ----------------------------------------------------------- metric-name

_DECLARED_METRICS_CACHE: Optional[Set[str]] = None


def _declared_metrics() -> Set[str]:
    """The DECLARED_METRICS literal parsed out of core/monitor.py (AST
    only — the lint never imports the framework)."""
    global _DECLARED_METRICS_CACHE
    if _DECLARED_METRICS_CACHE is not None:
        return _DECLARED_METRICS_CACHE
    from . import repo_root  # lazy: repo_root is defined after the
    #                          rules module is imported by __init__
    monitor_path = os.path.join(repo_root(), "paddle_tpu", "core",
                                "monitor.py")
    declared: Set[str] = set()
    try:
        with open(monitor_path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "DECLARED_METRICS"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        declared.add(sub.value)
    except OSError:
        pass
    _DECLARED_METRICS_CACHE = declared
    return declared


@rule("metric-name")
def check_metric_names(ctx: FileContext) -> List[LintFinding]:
    """Literal metric names passed to ``metrics.counter/gauge/
    histogram`` in the framework must be declared in
    ``core/monitor.DECLARED_METRICS``: an undeclared name is either a
    typo (the real counter stays 0 forever) or schema drift nobody can
    dashboard against."""
    if not ctx.relpath.startswith("paddle_tpu/") \
            or ctx.relpath in METRIC_NAME_EXEMPT or ctx.is_test_file:
        return []
    declared = _declared_metrics()
    if not declared:
        return []  # monitor.py unreadable: never cascade bogus findings
    findings = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and _dotted(node.func.value).split(".")[-1] == "metrics"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue  # dynamic names are the recorders' business
        name = node.args[0].value
        if name in declared or ctx.allowed(node, "metric-name"):
            continue
        findings.append(LintFinding(
            ctx.relpath, node.lineno, node.col_offset, "metric-name",
            f"metric {name!r} is not declared in "
            "core/monitor.DECLARED_METRICS; declare it there (with a "
            "docstring entry) or fix the typo"))
    return findings


# ------------------------------------------------------------- event-name

# the module that declares the event schema (and implements the ring):
# free to name events as it likes
_EVENT_NAME_EXEMPT = frozenset({"paddle_tpu/core/flight_recorder.py"})

_DECLARED_EVENTS_CACHE: Optional[Set[str]] = None


def _declared_events() -> Set[str]:
    """The DECLARED_EVENTS literal parsed out of core/flight_recorder.py
    (AST only, the _declared_metrics precedent)."""
    global _DECLARED_EVENTS_CACHE
    if _DECLARED_EVENTS_CACHE is not None:
        return _DECLARED_EVENTS_CACHE
    from . import repo_root
    fr_path = os.path.join(repo_root(), "paddle_tpu", "core",
                           "flight_recorder.py")
    declared: Set[str] = set()
    try:
        with open(fr_path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "DECLARED_EVENTS"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        declared.add(sub.value)
    except OSError:
        pass
    _DECLARED_EVENTS_CACHE = declared
    return _DECLARED_EVENTS_CACHE


@rule("event-name")
def check_event_names(ctx: FileContext) -> List[LintFinding]:
    """Literal event names passed to ``flight_recorder.record(...)``
    in the framework must be declared in
    ``core/flight_recorder.DECLARED_EVENTS``: an undeclared name is a
    stream no post-mortem tooling greps for and no docs/events.md row
    explains (the DECLARED_METRICS contract, applied to the black
    box). Span names (``record_span`` / ``Request.span``) are
    per-request dynamic and exempt; dynamic ``record(kind_var)``
    names are the recorders' business, same as metric-name."""
    if not ctx.relpath.startswith("paddle_tpu/") \
            or ctx.relpath in _EVENT_NAME_EXEMPT or ctx.is_test_file:
        return []
    declared = _declared_events()
    if not declared:
        return []  # flight_recorder.py unreadable: no bogus cascade
    findings = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and _dotted(node.func.value).split(".")[-1]
                == "flight_recorder"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if name in declared or ctx.allowed(node, "event-name"):
            continue
        findings.append(LintFinding(
            ctx.relpath, node.lineno, node.col_offset, "event-name",
            f"flight-recorder event {name!r} is not declared in "
            "core/flight_recorder.DECLARED_EVENTS; declare it there "
            "(with an EVENT_DOC entry) or fix the typo"))
    return findings


# ------------------------------------------------------------ dead-metric

_RECORDED_NAMES_CACHE = None  # (literals: Set[str], patterns: List[regex])


def _recording_calls(tree: ast.Module):
    """(literal names, f-string regexes) from every ``metrics.counter/
    gauge/histogram(...)`` first argument in one module. F-string names
    (``f"{target}.compile"``) become anchored regexes with ``.+`` at
    each formatted field, so dynamically-prefixed recordings still
    count as live."""
    import re
    literals: Set[str] = set()
    patterns = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and _dotted(node.func.value).split(".")[-1] == "metrics"):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            literals.add(arg.value)
        elif isinstance(arg, ast.JoinedStr):
            parts = []
            for v in arg.values:
                if isinstance(v, ast.Constant):
                    parts.append(re.escape(str(v.value)))
                else:
                    parts.append(".+")
            patterns.append(re.compile("^" + "".join(parts) + "$"))
    return literals, patterns


def _recorded_names():
    """Every metric name recorded anywhere under paddle_tpu/ (scanned
    once per process, stdlib ast only)."""
    global _RECORDED_NAMES_CACHE
    if _RECORDED_NAMES_CACHE is not None:
        return _RECORDED_NAMES_CACHE
    from . import repo_root
    literals: Set[str] = set()
    patterns: list = []
    pkg = os.path.join(repo_root(), "paddle_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fn), "r",
                          encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            lit, pat = _recording_calls(tree)
            literals |= lit
            patterns += pat
    _RECORDED_NAMES_CACHE = (literals, patterns)
    return _RECORDED_NAMES_CACHE


@rule("dead-metric")
def check_dead_metrics(ctx: FileContext) -> List[LintFinding]:
    """Every name in ``DECLARED_METRICS`` must be RECORDED somewhere
    under ``paddle_tpu/`` (a ``metrics.counter/gauge/histogram`` call,
    literal or f-string first arg — the same AST machinery as
    ``metric-name``, pointed the other way). A declared-but-never-
    recorded name is schema rot: dashboards and docs promise a series
    that will sit at zero forever. Fires on the module that declares
    the schema (``DECLARED_METRICS`` assignment in a paddle_tpu core
    module), so the finding lands on the stale declaration line."""
    if not ctx.relpath.startswith("paddle_tpu/core/") \
            or ctx.is_test_file:
        return []
    declared_nodes = []  # (name, lineno, col)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "DECLARED_METRICS"
                for t in node.targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str):
                    declared_nodes.append(
                        (sub.value, sub.lineno, sub.col_offset))
    if not declared_nodes:
        return []
    literals, patterns = _recorded_names()
    # the declaring module's own recorders count too (snippet tests
    # lint a synthetic monitor.py that is not under the real package)
    own_lit, own_pat = _recording_calls(ctx.tree)
    literals = literals | own_lit
    patterns = patterns + own_pat
    findings = []
    for name, line, col in declared_nodes:
        if name in literals or any(p.match(name) for p in patterns):
            continue
        node = ast.Constant(value=name)
        node.lineno, node.col_offset, node.end_lineno = line, col, line
        if ctx.allowed(node, "dead-metric"):
            continue
        findings.append(LintFinding(
            ctx.relpath, line, col, "dead-metric",
            f"metric {name!r} is declared in DECLARED_METRICS but never "
            "recorded anywhere under paddle_tpu/ (no metrics.counter/"
            "gauge/histogram call names it); wire a recorder or drop "
            "the declaration"))
    return findings


# ------------------------------------------------------ compile-cache-dir

# the one module allowed to touch jax's process-global compile-cache
# config (owns the set-once + conflict-warning semantics)
_COMPILE_CACHE_OWNER = "paddle_tpu/jit/compile_cache.py"


@rule("compile-cache-dir")
def check_compile_cache_dir(ctx: FileContext) -> List[LintFinding]:
    """Direct ``jax.config.update("jax_compilation_cache_dir", ...)``
    outside ``jit/compile_cache.py``: the jax cache dir is
    process-global state — a stray update silently re-points (or races)
    every other subsystem's cache, the predictor global-hijack bug
    class. Call ``paddle_tpu.jit.enable_compile_cache(dir)`` instead;
    it owns the set-once/warn-on-conflict semantics."""
    if ctx.relpath == _COMPILE_CACHE_OWNER:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _dotted(node.func).endswith("config.update")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_compilation_cache_dir"):
            continue
        if ctx.allowed(node, "compile-cache-dir"):
            continue
        findings.append(LintFinding(
            ctx.relpath, node.lineno, node.col_offset,
            "compile-cache-dir",
            "direct jax.config.update('jax_compilation_cache_dir', ...) "
            "re-points process-global state under every other "
            "subsystem; use paddle_tpu.jit.enable_compile_cache(dir) "
            "(jit/compile_cache.py owns the set-once semantics)"))
    return findings


# ------------------------------------------------------- lock-discipline

# Shared mutable state that MUST be written under a lock: the scheduler
# thread, the telemetry HTTP thread, and Future.result() pumps all
# touch these concurrently (the PR-12 telemetry-thread race class).
# relpath -> {class name -> protected attribute names}. Writes are
# legal (a) lexically inside a ``with self.<...lock...>:`` block, (b)
# in ``__init__`` (single-threaded construction), or (c) on a line /
# in a method whose def line carries ``# lint: lock-discipline-ok
# (reason)`` — the "caller holds the lock" helpers.
LOCK_DISCIPLINE = {
    "paddle_tpu/generation/paged_cache.py": {
        "PageAllocator": frozenset({
            "_free", "_ref", "_prefix", "_page_key"}),
    },
    "paddle_tpu/serving/engine.py": {
        "ServingEngine": frozenset({
            "_queue", "_slots", "_slot_used"}),
    },
    "paddle_tpu/serving/router.py": {
        "FleetRouter": frozenset({
            "_replicas", "_stats"}),
    },
}

# deque/list/dict/OrderedDict methods that mutate their receiver
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "move_to_end", "sort", "reverse", "add", "discard",
})


def _protected_attr(node: ast.AST, attrs) -> Optional[str]:
    """The protected ``self.X`` attribute a node writes/mutates, if
    any: plain/aug/subscript assignment targets and mutator-method
    calls on ``self.X``."""
    def self_attr(n):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "self" \
                and n.attr in attrs:
            return n.attr
        return None

    if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
        targets = [node.target] if isinstance(node, ast.AugAssign) \
            else node.targets
        for t in targets:
            for el in ast.walk(t):
                if isinstance(el, ast.Subscript):
                    hit = self_attr(el.value)
                    if hit:
                        return hit
                hit = self_attr(el)
                if hit:
                    return hit
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATOR_METHODS:
        recv = node.func.value
        if isinstance(recv, ast.Subscript):
            recv = recv.value
        return self_attr(recv)
    return None


def _lock_with_items(with_node: ast.With) -> bool:
    """True when the with-statement enters ``self.<something lock>``
    (``self._lock``, ``self._qlock``, ``self._pump_lock``, including
    ``.acquire()``-less RLock reentry)."""
    for item in with_node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and "lock" in n.attr \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                return True
    return False


def _def_line_marked(ctx: FileContext, fn: ast.AST, rule_name: str) -> bool:
    """Marker on the method's def line (or a decorator line): the
    whole body is exempt — the 'caller holds self._lock' helpers."""
    token = f"lint: {rule_name}-ok"
    lines = [fn.lineno] + [d.lineno for d in
                           getattr(fn, "decorator_list", [])]
    return any(token in ctx.lines[ln - 1] for ln in lines
               if 0 < ln <= len(ctx.lines))


@rule("lock-discipline")
def check_lock_discipline(ctx: FileContext) -> List[LintFinding]:
    """Writes to the allocator free-list/refcount maps and the engine
    queue/slot tables outside a ``with self._lock``-style block: the
    statically-catchable form of the PR-12 telemetry-thread race (an
    HTTP scrape iterating ``self._free`` mid-mutation). Helpers whose
    caller holds the lock mark their def line ``# lint:
    lock-discipline-ok (caller holds self._lock)``."""
    scopes = LOCK_DISCIPLINE.get(ctx.relpath)
    if not scopes:
        return []
    findings = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef) or cls.name not in scopes:
            continue
        attrs = scopes[cls.name]
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or \
                    _def_line_marked(ctx, fn, "lock-discipline"):
                continue

            def walk_fn(node, locked):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue  # nested defs run elsewhere
                    child_locked = locked or (
                        isinstance(child, ast.With)
                        and _lock_with_items(child))
                    if not child_locked:
                        hit = _protected_attr(child, attrs)
                        if hit and not ctx.allowed(
                                child, "lock-discipline"):
                            findings.append(LintFinding(
                                ctx.relpath, child.lineno,
                                child.col_offset, "lock-discipline",
                                f"write to self.{hit} outside a 'with "
                                "self._lock' block: another thread "
                                "(telemetry scrape, Future.result "
                                "pump) can observe it mid-mutation; "
                                "take the lock, or mark the line/def "
                                "'# lint: lock-discipline-ok (reason)'"
                                " if the caller holds it"))
                    walk_fn(child, child_locked)

            walk_fn(fn, False)
    return findings


# ---------------------------------------------------------- chaos-marker

def _has_chaos_marker(nodes: List[ast.AST]) -> bool:
    """True if any node in the chain (module, class, function) carries
    a pytest chaos marker: module-level ``pytestmark = ...chaos...`` or
    a ``@pytest.mark.chaos`` decorator."""
    for node in nodes:
        if isinstance(node, ast.Module):
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "pytestmark"
                        for t in stmt.targets):
                    if any(isinstance(s, ast.Attribute) and s.attr == "chaos"
                           for s in ast.walk(stmt.value)):
                        return True
        else:
            for dec in getattr(node, "decorator_list", []):
                if any(isinstance(s, ast.Attribute) and s.attr == "chaos"
                       for s in ast.walk(dec)):
                    return True
    return False


@rule("chaos-marker")
def check_chaos_marker(ctx: FileContext) -> List[LintFinding]:
    """Tests importing ``paddle_tpu.utils.fault_injection`` must carry
    the ``chaos`` marker — module-level ``pytestmark`` or a decorator
    on the enclosing test/class — so ``pytest -m chaos`` runs the whole
    chaos tier and ``-m 'not chaos'`` really excludes it. This promotes
    the conftest collection guard (module-level imports only) to lint,
    which also sees function-level imports."""
    if not ctx.is_test_file or "conftest" in os.path.basename(ctx.relpath):
        return []
    findings = []

    def _imports_fi(node) -> bool:
        if isinstance(node, ast.Import):
            return any(a.name.startswith(_FAULT_INJECTION_MODULE)
                       for a in node.names)
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith(_FAULT_INJECTION_MODULE):
                return True
            return mod == "paddle_tpu.utils" and any(
                a.name == "fault_injection" for a in node.names)
        return False

    def _walk(node, chain):
        for child in ast.iter_child_nodes(node):
            if _imports_fi(child):
                if not _has_chaos_marker(chain) and \
                        not ctx.allowed(child, "chaos-marker"):
                    findings.append(LintFinding(
                        ctx.relpath, child.lineno, child.col_offset,
                        "chaos-marker",
                        "imports paddle_tpu.utils.fault_injection "
                        "without a chaos marker on the module "
                        "(pytestmark), class, or test: add "
                        "@pytest.mark.chaos"))
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                _walk(child, chain + [child])
            else:
                _walk(child, chain)

    _walk(ctx.tree, [ctx.tree])
    return findings
