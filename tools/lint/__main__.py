"""CLI: ``python -m tools.lint paddle_tpu tests`` — nonzero exit on any
finding (the tier-1 gate shells exactly this)."""
from __future__ import annotations

import argparse
import sys

from . import RULES, lint_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="paddle_tpu framework lint (stdlib-ast static "
                    "checks; see tools/lint/__init__.py for the rules "
                    "and the allowlist-marker syntax)")
    parser.add_argument("paths", nargs="*", default=["paddle_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: paddle_tpu tests)")
    parser.add_argument("--rules", action="store_true",
                        help="list rule ids and exit")
    args = parser.parse_args(argv)
    if args.rules:
        for name, fn in sorted(RULES.items()):
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{name:14} {doc}")
        return 0
    stats = {}
    try:
        findings = lint_paths(args.paths or ["paddle_tpu", "tests"],
                              stats=stats)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f)
    # the file count proves the walk matched something — a path typo
    # (or a bad cwd) must read as "0 files", never as a clean pass
    print(f"{stats['files']} file(s), {len(findings)} finding(s)",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
