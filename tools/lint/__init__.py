"""Framework lint: stdlib-`ast` static checks for the Python layer.

The program auditor (``paddle_tpu.analysis``) guards what a TRACED
program may contain; this lint guards the Python that builds and drives
those programs — the host-side hazards no jaxpr ever shows:

    host-sync     eager ``.numpy()`` / ``float(x)`` / ``np.asarray(x)``
                  in hot-path modules outside allowlisted sync points
    jit-random    Python/`np.random` randomness inside functions that
                  get jitted (baked into the trace as constants)
    bare-except   ``except:`` that swallows without
                  ``monitor.record_swallowed`` (silent failure — the
                  fault-tolerance layer's cardinal sin)
    metric-name   metric names recorded that are not declared in
                  ``core/monitor.DECLARED_METRICS`` (typo'd counters
                  nobody will ever read)
    dead-metric   names declared in ``DECLARED_METRICS`` that no
                  ``metrics.counter/gauge/histogram`` call under
                  ``paddle_tpu/`` ever records (schema rot: a series
                  promised to dashboards that stays zero forever)
    chaos-marker  tests importing ``utils.fault_injection`` without the
                  ``chaos`` marker (the conftest collection guard,
                  promoted to lint so function-level imports are caught
                  too)
    compile-cache-dir  direct ``jax.config.update(
                  "jax_compilation_cache_dir", ...)`` outside
                  ``jit/compile_cache.py`` (process-global cache-dir
                  hijack; call ``jit.enable_compile_cache``)

Run it over the tree (CI does; nonzero exit on any finding):

    python -m tools.lint paddle_tpu tests

Allowlist a deliberate violation with a same-line marker naming the
rule, e.g. ``np.asarray(ids)  # lint: host-sync-ok (pre-dispatch)`` —
the reason in parentheses is for the reviewer, the token before it is
what the lint matches.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable, Dict, List, Optional

REPO_RULE_DOC = __doc__


@dataclasses.dataclass(frozen=True)
class LintFinding:
    path: str      # repo-relative, posix
    line: int
    col: int
    rule: str
    message: str

    def __str__(self):
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")


class FileContext:
    """One parsed source file plus the helpers rules share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)

    @property
    def is_test_file(self) -> bool:
        base = os.path.basename(self.relpath)
        return (self.relpath.split("/")[0] == "tests"
                or base.startswith("test_") or base == "conftest.py")

    def allowed(self, node: ast.AST, rule: str) -> bool:
        """True when any line the node spans carries the rule's
        ``# lint: <rule>-ok`` marker (calls often wrap lines; the
        marker may sit on whichever physical line survives the
        formatter)."""
        first = getattr(node, "lineno", 0)
        last = getattr(node, "end_lineno", first) or first
        token = f"lint: {rule}-ok"
        return any(token in self.lines[i - 1]
                   for i in range(max(first, 1),
                                  min(last, len(self.lines)) + 1))


RuleFn = Callable[[FileContext], List[LintFinding]]
RULES: Dict[str, RuleFn] = {}


def rule(name: str):
    def deco(fn: RuleFn):
        RULES[name] = fn
        return fn
    return deco


# imported for the side effect of registering the rules
from . import rules  # noqa: E402,F401


def lint_file(path: str, relpath: str) -> List[LintFinding]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        ctx = FileContext(path, relpath, source)
    except SyntaxError as e:
        return [LintFinding(relpath, e.lineno or 0, e.offset or 0,
                            "syntax", f"unparseable: {e.msg}")]
    findings: List[LintFinding] = []
    for fn in RULES.values():
        findings.extend(fn(ctx))
    return findings


def repo_root() -> str:
    """The repository this lint ships in (tools/lint/ lives two levels
    below it). Path-scoped rules key on repo-relative paths, so this —
    never the cwd — anchors relpath computation: the lint must behave
    identically invoked from the repo root, a neutral cwd with absolute
    paths, or CI."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def lint_paths(paths: List[str], root: Optional[str] = None,
               stats: Optional[dict] = None) -> List[LintFinding]:
    """Lint every .py under ``paths`` (files or directories; relative
    paths resolve against ``root``, default the repo root — NOT the
    cwd, so the path-scoped rules fire no matter where the lint is
    invoked from). Returns findings sorted by location; ``stats`` (if
    given) receives ``{'files': N}`` so callers can prove the walk
    matched something."""
    root = os.path.abspath(root if root is not None else repo_root())
    files: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            # a typo'd path must fail, never read as a clean pass (CI
            # green-forever on `tools.lint paddel_tpu` is the failure
            # mode this guards)
            raise FileNotFoundError(
                f"lint path {p!r} does not exist (resolved {full!r})")
        if os.path.isfile(full):
            files.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            files.extend(os.path.join(dirpath, f)
                         for f in filenames if f.endswith(".py"))
    files = sorted(set(files))
    if stats is not None:
        stats["files"] = len(files)
    findings: List[LintFinding] = []
    for f in files:
        findings.extend(lint_file(f, os.path.relpath(f, root)))
    return sorted(findings, key=lambda x: (x.path, x.line, x.col))
