"""Generate docs/metrics.md AND docs/events.md from the declared
schemas.

The registry's schema lives in ``core/monitor.py`` twice: the
``DECLARED_METRICS`` frozenset the framework lint enforces (an
undeclared name recorded anywhere in ``paddle_tpu/`` fails CI) and the
``METRIC_DOC`` table carrying each name's kind, labels and description.
The flight recorder's event schema lives the same way in
``core/flight_recorder.py`` (``DECLARED_EVENTS`` enforced by the
lint's ``event-name`` rule, ``EVENT_DOC`` for descriptions). This tool
renders both tables as markdown references, and the tier-1 drift tests
regenerate them on every run — a schema change that forgets the doc
(or a doc edit that drifts from the schema) fails CI.

    python -m tools.metrics_doc            # rewrite both docs
    python -m tools.metrics_doc --check    # exit 1 if either is stale
"""
from __future__ import annotations

import os
import sys

_HEADER = """\
# Metrics reference

<!-- GENERATED FILE — do not edit by hand.
     Regenerate with `python -m tools.metrics_doc`; the schema lives in
     `paddle_tpu/core/monitor.py` (METRIC_DOC / DECLARED_METRICS). -->

Every metric the framework records, as declared in
`core/monitor.DECLARED_METRICS`. All of them flow through the
process-global registry (`core/metrics.py`): scrape them live from the
telemetry server's `/metrics` (Prometheus text; dots become
underscores, label sets render as `{k="v"}`), snapshot them with
`profiler.metrics.snapshot()`, or watch them as counter tracks in the
Perfetto export. Labeled metrics also keep an unlabeled aggregate
under the same name.

| Metric | Kind | Labels | Description |
|---|---|---|---|
"""


def render() -> str:
    from paddle_tpu.core.monitor import DECLARED_METRICS, METRIC_DOC
    missing = DECLARED_METRICS - set(METRIC_DOC)
    extra = set(METRIC_DOC) - DECLARED_METRICS
    if missing or extra:
        raise SystemExit(
            f"METRIC_DOC out of sync with DECLARED_METRICS: "
            f"missing={sorted(missing)} extra={sorted(extra)}")
    rows = []
    for name in sorted(METRIC_DOC):
        kind, labels, desc = METRIC_DOC[name]
        lab = ", ".join(labels) if labels else "—"
        rows.append(f"| `{name}` | {kind} | {lab} | {desc} |")
    return _HEADER + "\n".join(rows) + "\n"


_EVENTS_HEADER = """\
# Flight-recorder events reference

<!-- GENERATED FILE — do not edit by hand.
     Regenerate with `python -m tools.metrics_doc`; the schema lives in
     `paddle_tpu/core/flight_recorder.py` (EVENT_DOC /
     DECLARED_EVENTS). -->

Every structured point event the framework records into the flight
recorder's ring, as declared in `core/flight_recorder.DECLARED_EVENTS`
(enforced by the `event-name` lint rule). Events surface in auto-dumps
(Perfetto JSON + plaintext tail), `/flightrecorder`, and — merged
across ranks by `tools/trace_merge.py` — the fleet post-mortem
timeline. Request-trace SPANS carry dynamic per-request names and are
not listed here.

| Event | Description |
|---|---|
"""


def render_events() -> str:
    from paddle_tpu.core.flight_recorder import (DECLARED_EVENTS,
                                                 EVENT_DOC)
    missing = DECLARED_EVENTS - set(EVENT_DOC)
    extra = set(EVENT_DOC) - DECLARED_EVENTS
    if missing or extra:
        raise SystemExit(
            f"EVENT_DOC out of sync with DECLARED_EVENTS: "
            f"missing={sorted(missing)} extra={sorted(extra)}")
    rows = [f"| `{name}` | {EVENT_DOC[name]} |"
            for name in sorted(EVENT_DOC)]
    return _EVENTS_HEADER + "\n".join(rows) + "\n"


def _docs_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "docs")


def doc_path() -> str:
    return os.path.join(_docs_dir(), "metrics.md")


def events_doc_path() -> str:
    return os.path.join(_docs_dir(), "events.md")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    rc = 0
    for path, text in ((doc_path(), render()),
                       (events_doc_path(), render_events())):
        if "--check" in argv:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    current = f.read()
            except OSError:
                current = ""
            if current != text:
                sys.stderr.write(
                    f"{path} is stale; regenerate with "
                    "`python -m tools.metrics_doc`\n")
                rc = 1
            continue
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        sys.stderr.write(f"wrote {path}\n")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
