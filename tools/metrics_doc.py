"""Generate docs/metrics.md from core/monitor's declared metric schema.

The registry's schema lives in ``core/monitor.py`` twice: the
``DECLARED_METRICS`` frozenset the framework lint enforces (an
undeclared name recorded anywhere in ``paddle_tpu/`` fails CI) and the
``METRIC_DOC`` table carrying each name's kind, labels and description.
This tool renders the table as a markdown reference, and the tier-1
drift test (``tests/test_telemetry.py``) regenerates it on every run —
a schema change that forgets the doc (or a doc edit that drifts from
the schema) fails CI, the same contract the lint's ``dead-metric`` rule
applies to the recording side.

    python -m tools.metrics_doc            # rewrite docs/metrics.md
    python -m tools.metrics_doc --check    # exit 1 if stale
"""
from __future__ import annotations

import os
import sys

_HEADER = """\
# Metrics reference

<!-- GENERATED FILE — do not edit by hand.
     Regenerate with `python -m tools.metrics_doc`; the schema lives in
     `paddle_tpu/core/monitor.py` (METRIC_DOC / DECLARED_METRICS). -->

Every metric the framework records, as declared in
`core/monitor.DECLARED_METRICS`. All of them flow through the
process-global registry (`core/metrics.py`): scrape them live from the
telemetry server's `/metrics` (Prometheus text; dots become
underscores, label sets render as `{k="v"}`), snapshot them with
`profiler.metrics.snapshot()`, or watch them as counter tracks in the
Perfetto export. Labeled metrics also keep an unlabeled aggregate
under the same name.

| Metric | Kind | Labels | Description |
|---|---|---|---|
"""


def render() -> str:
    from paddle_tpu.core.monitor import DECLARED_METRICS, METRIC_DOC
    missing = DECLARED_METRICS - set(METRIC_DOC)
    extra = set(METRIC_DOC) - DECLARED_METRICS
    if missing or extra:
        raise SystemExit(
            f"METRIC_DOC out of sync with DECLARED_METRICS: "
            f"missing={sorted(missing)} extra={sorted(extra)}")
    rows = []
    for name in sorted(METRIC_DOC):
        kind, labels, desc = METRIC_DOC[name]
        lab = ", ".join(labels) if labels else "—"
        rows.append(f"| `{name}` | {kind} | {lab} | {desc} |")
    return _HEADER + "\n".join(rows) + "\n"


def doc_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "docs", "metrics.md")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    text = render()
    path = doc_path()
    if "--check" in argv:
        try:
            with open(path, "r", encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            sys.stderr.write(
                f"{path} is stale; regenerate with "
                "`python -m tools.metrics_doc`\n")
            return 1
        return 0
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    sys.stderr.write(f"wrote {path}\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
