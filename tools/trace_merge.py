"""Merge N per-rank flight-recorder dumps into ONE clock-aligned
Perfetto JSON — the fleet post-mortem viewer.

Each rank of a killed job leaves its own dump under the shared
``PADDLE_FLIGHT_RECORDER_DIR`` (filenames embed ``(rank, restart,
pid)`` so they never clobber). Every dump's metadata carries the
per-process clock mapping the recorder stamps at dump time:

    anchor_wall_ns / anchor_perf_ns   perf_counter -> wall clock
    clock_offset_ns                   this host's wall clock vs the
                                      fleet store's master clock (the
                                      fleet-telemetry ping handshake)
    rank / restart_count / pid        the track identity

This tool maps every event's monotonic timestamp through those three
terms onto one shared timeline (the store master's clock), rebases at
the earliest event, and emits a single trace with ONE named process
track per ``(rank, incarnation)`` — so a kill-one-worker chaos run
renders as SIGTERM on rank k beside the detection/recovery spans on
its peers, correctly ordered even when the hosts' clocks disagree.

    python -m tools.trace_merge -o merged.json dump_a.json dump_b.json
    python -m tools.trace_merge -o merged.json /path/to/dump/dir

A directory argument globs its ``flightrecorder_*.json`` dumps. Dumps
from before the clock-mapping metadata existed are merged with offset
0 and a warning in the output metadata (ordering across such ranks is
best-effort).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["merge", "merge_paths", "main"]


def _collect_paths(args: List[str]) -> List[str]:
    paths: List[str] = []
    for a in args:
        if os.path.isdir(a):
            found = sorted(glob.glob(
                os.path.join(a, "flightrecorder_*.json")))
            if not found:
                raise FileNotFoundError(
                    f"no flightrecorder_*.json dumps under {a}")
            paths.extend(found)
        else:
            paths.append(a)
    if not paths:
        raise ValueError("no dump paths given")
    return paths


def _track_key(md: dict) -> Tuple[int, int]:
    return int(md.get("rank", 0)), int(md.get("restart_count", 0))


def _aligned_wall_ns(ts_us: float, md: dict) -> Optional[float]:
    """One event's Perfetto ``ts`` (µs of perf_counter) -> ns on the
    shared master clock; None when the dump predates the anchors."""
    aw = md.get("anchor_wall_ns")
    ap = md.get("anchor_perf_ns")
    if aw is None or ap is None:
        return None
    wall = aw + (ts_us * 1000.0 - ap)
    return wall - md.get("clock_offset_ns", 0)


def merge(dumps: List[dict]) -> dict:
    """Merge loaded dump dicts (``flight_recorder.dump_dict`` /
    ``.json`` file contents) into one Perfetto trace dict."""
    if not dumps:
        raise ValueError("no dumps to merge")
    tracks: Dict[Tuple[int, int], dict] = {}
    staged = []   # (track, pid, aligned_ns_or_None, raw_ts_us, event)
    unaligned_tracks = set()
    seen: Dict[Tuple[int, int], set] = {}
    for d in dumps:
        md = d.get("metadata", {})
        key = _track_key(md)
        pid = int(md.get("pid", 0))
        if key in tracks and tracks[key]["pid"] != pid:
            # same (rank, incarnation) from two different processes:
            # two jobs' dumps were mixed into one merge call
            raise ValueError(
                f"duplicate track rank{key[0]}.{key[1]} from pids "
                f"{tracks[key]['pid']} and {pid}: merging dumps of "
                "two different jobs?")
        if key in tracks:
            # a SECOND dump from the same process (auto_dump at
            # preemption + a later crash/manual dump): merge the
            # union of both rings — overlapping events dedupe below
            tracks[key]["dropped"] = max(tracks[key]["dropped"],
                                         md.get("dropped_events", 0))
            reason = md.get("reason", "?")
            if reason not in tracks[key]["reason"].split("+"):
                tracks[key]["reason"] += f"+{reason}"
        else:
            tracks[key] = {
                "pid": pid,
                "offset_ns": md.get("clock_offset_ns", 0),
                "events": 0,
                "dropped": md.get("dropped_events", 0),
                "reason": md.get("reason", "?"),
            }
            seen[key] = set()
        for ev in d.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue   # per-process metadata rebuilt below
            # dumps of one process share the ring (and its anchors):
            # identical events from overlapping dumps render ONCE
            fp = json.dumps(ev, sort_keys=True, default=str)
            if fp in seen[key]:
                continue
            seen[key].add(fp)
            aligned = _aligned_wall_ns(float(ev.get("ts", 0.0)), md)
            if aligned is None:
                unaligned_tracks.add(key)
            staged.append((key, pid, aligned, float(ev.get("ts", 0.0)),
                           ev))
            tracks[key]["events"] += 1
    aligned_vals = [a for _, _, a, _, _ in staged if a is not None]
    base_ns = min(aligned_vals) if aligned_vals else 0.0
    out_events = []
    for rank, restart in sorted(tracks):
        t = tracks[(rank, restart)]
        out_events.append({
            "name": "process_name", "ph": "M", "pid": t["pid"],
            "tid": 0,
            "args": {"name": f"rank{rank}.{restart} "
                             f"(pid {t['pid']}, {t['reason']})"}})
        out_events.append({
            "name": "process_sort_index", "ph": "M", "pid": t["pid"],
            "tid": 0, "args": {"sort_index": rank}})
    for key, pid, aligned, raw_us, ev in staged:
        e = dict(ev)
        e["pid"] = pid
        # unaligned legacy dumps keep their raw timeline (offset 0)
        e["ts"] = (aligned - base_ns) / 1000.0 \
            if aligned is not None else raw_us
        out_events.append(e)
    return {
        "traceEvents": out_events,
        "metadata": {
            "merged_tracks": {
                f"rank{r}.{i}": tracks[(r, i)]
                for r, i in sorted(tracks)},
            "base_wall_ns": base_ns,
            "clock_aligned": not unaligned_tracks,
            **({"unaligned_tracks":
                sorted(f"rank{r}.{i}" for r, i in unaligned_tracks)}
               if unaligned_tracks else {}),
        },
    }


def merge_paths(paths: List[str]) -> dict:
    dumps = []
    for p in _collect_paths(paths):
        with open(p, "r", encoding="utf-8") as f:
            dumps.append(json.load(f))
    return merge(dumps)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.trace_merge",
        description="Merge per-rank flight-recorder dumps into one "
                    "clock-aligned Perfetto JSON.")
    p.add_argument("-o", "--output", required=True,
                   help="merged Perfetto JSON output path")
    p.add_argument("dumps", nargs="+",
                   help="dump .json files, or directories to glob "
                        "flightrecorder_*.json from")
    args = p.parse_args(argv)
    merged = merge_paths(args.dumps)
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    md = merged["metadata"]
    sys.stderr.write(
        f"merged {len(md['merged_tracks'])} track(s), "
        f"{len(merged['traceEvents'])} events -> {args.output}"
        f"{'' if md['clock_aligned'] else ' (NOT clock-aligned)'}\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
