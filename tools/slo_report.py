"""Render the SLO story out of flight-recorder dumps as a plaintext
post-mortem — the ops-facing sibling of ``tools.trace_merge``.

A killed or misbehaving job leaves per-rank dumps under
``PADDLE_FLIGHT_RECORDER_DIR``; the watchtower (``core.slo``) has been
writing its alert transitions into the same ring the whole time:

    slo.pending / slo.firing / slo.resolved   instant events with the
                                              burn rates + measured
                                              value at transition time
    slo:<name> spans                          escalation (pending ->
                                              firing) and firing
                                              (firing -> resolved)
                                              periods
    train.straggler                           detected/resolved per
                                              rank, with the robust
                                              z-score that tripped it

This tool collects those events across one or many dumps, aligns them
on the shared master clock when the dumps carry the PR-14 clock
anchors (same mapping ``tools.trace_merge`` uses), and prints three
tables: the alert timeline, the alert periods with durations, and the
straggler history. The point is a ``less``-able answer to "what was
firing when the job died" without opening Perfetto.

    python -m tools.slo_report dump_a.json dump_b.json
    python -m tools.slo_report /path/to/dump/dir
    python -m tools.slo_report -o postmortem.txt dumps/

A directory argument globs its ``flightrecorder_*.json`` dumps.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

__all__ = ["load_paths", "report", "main"]

_ALERT_EVENTS = ("slo.pending", "slo.firing", "slo.resolved")


def _collect_paths(args: List[str]) -> List[str]:
    paths: List[str] = []
    for a in args:
        if os.path.isdir(a):
            found = sorted(glob.glob(
                os.path.join(a, "flightrecorder_*.json")))
            if not found:
                raise FileNotFoundError(
                    f"no flightrecorder_*.json dumps under {a}")
            paths.extend(found)
        else:
            paths.append(a)
    if not paths:
        raise ValueError("no dump paths given")
    return paths


def load_paths(paths: List[str]) -> List[dict]:
    dumps = []
    for p in _collect_paths(paths):
        with open(p, "r", encoding="utf-8") as f:
            dumps.append(json.load(f))
    return dumps


def _aligned_wall_ns(ts_us: float, md: dict) -> Optional[float]:
    aw = md.get("anchor_wall_ns")
    ap = md.get("anchor_perf_ns")
    if aw is None or ap is None:
        return None
    return aw + (ts_us * 1000.0 - ap) - md.get("clock_offset_ns", 0)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def _table(header: List[str], rows: List[List[str]]) -> List[str]:
    widths = [len(h) for h in header]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    out = ["  ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip(),
           "  ".join("-" * w for w in widths)]
    for r in rows:
        out.append("  ".join(c.ljust(w)
                             for c, w in zip(r, widths)).rstrip())
    return out


def report(dumps: List[dict]) -> str:
    """Plaintext SLO post-mortem for loaded dump dicts
    (``flight_recorder.dump_dict`` / ``.json`` file contents)."""
    if not dumps:
        raise ValueError("no dumps to report on")
    alerts = []     # (t_ns, track, slo, transition, args)
    spans = []      # (t_ns, track, slo, phase, dur_s)
    stragglers = [] # (t_ns, track, rank, phase, args)
    tracks = []
    seen = set()
    for d in dumps:
        md = d.get("metadata", {})
        track = f"rank{md.get('rank', 0)}.{md.get('restart_count', 0)}"
        tracks.append(f"{track} (pid {md.get('pid', '?')}, "
                      f"{md.get('reason', '?')}, "
                      f"{md.get('events', '?')} events)")
        for ev in d.get("traceEvents", []):
            name = ev.get("name", "")
            ph = ev.get("ph")
            interesting = (
                (ph == "i" and (name in _ALERT_EVENTS
                                or name == "train.straggler"))
                or (ph == "X" and name.startswith("slo:")))
            if not interesting:
                continue
            # overlapping dumps of one ring render each event once
            fp = json.dumps(ev, sort_keys=True, default=str)
            if fp in seen:
                continue
            seen.add(fp)
            ts_us = float(ev.get("ts", 0.0))
            t_ns = _aligned_wall_ns(ts_us, md)
            if t_ns is None:
                t_ns = ts_us * 1000.0   # legacy dump: raw timeline
            args = ev.get("args", {}) or {}
            if ph == "X":
                spans.append((t_ns, track, name[len("slo:"):],
                              args.get("phase", "?"),
                              float(ev.get("dur", 0.0)) / 1e6))
            elif name == "train.straggler":
                stragglers.append((t_ns, track, args.get("rank", "?"),
                                   args.get("phase", "?"), args))
            else:
                alerts.append((t_ns, track, args.get("slo", "?"),
                               name.split(".", 1)[1], args))
    base_ns = min((t for t, *_ in alerts + spans + stragglers),
                  default=0.0)

    def rel(t_ns: float) -> str:
        return f"{(t_ns - base_ns) / 1e9:+.3f}s"

    lines = ["SLO post-mortem over " + str(len(dumps)) + " dump(s):"]
    lines += [f"  {t}" for t in sorted(tracks)]
    lines.append("")
    lines.append("Alert timeline")
    if alerts:
        rows = []
        for t, track, slo, to, a in sorted(alerts):
            extra = f"firing_s={_fmt(a['firing_s'])}" \
                if "firing_s" in a else ""
            rows.append([rel(t), track, slo, to,
                         _fmt(a.get("burn_fast", "?")),
                         _fmt(a.get("burn_slow", "?")),
                         _fmt(a.get("measured", "?")), extra])
        lines += _table(["time", "track", "slo", "->", "burn_fast",
                         "burn_slow", "measured", ""], rows)
    else:
        lines.append("  (no slo.* transitions in these dumps)")
    lines.append("")
    lines.append("Alert periods")
    if spans:
        rows = [[rel(t), track, slo, phase, f"{dur:.3f}s"]
                for t, track, slo, phase, dur in sorted(spans)]
        lines += _table(["start", "track", "slo", "phase", "duration"],
                        rows)
    else:
        lines.append("  (no slo:* spans in these dumps)")
    lines.append("")
    lines.append("Stragglers")
    if stragglers:
        rows = [[rel(t), track, str(rank), phase,
                 _fmt(a.get("z", "?")), _fmt(a.get("mean_s", "?")),
                 _fmt(a.get("median_s", "?"))]
                for t, track, rank, phase, a in sorted(
                    stragglers, key=lambda r: (r[0], str(r[2])))]
        lines += _table(["time", "track", "rank", "phase", "z",
                         "mean_s", "median_s"], rows)
    else:
        lines.append("  (no train.straggler events in these dumps)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.slo_report",
        description="Render SLO alert + straggler history from "
                    "flight-recorder dumps as a plaintext post-mortem.")
    p.add_argument("-o", "--output", default=None,
                   help="write the report here instead of stdout")
    p.add_argument("dumps", nargs="+",
                   help="dump .json files, or directories to glob "
                        "flightrecorder_*.json from")
    args = p.parse_args(argv)
    text = report(load_paths(args.dumps))
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
        sys.stderr.write(f"wrote {args.output}\n")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
