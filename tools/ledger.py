"""Refresh / check the committed program ledger (docs/programs.json).

The ledger freezes every flagship program's audited signature —
structural fingerprint, donation coverage, planned peak HBM bytes,
per-axis collective bytes, finding counts — so capacity-relevant drift
fails CI as a JSON diff (see ``paddle_tpu/analysis/ledger.py``).

    python -m tools.ledger --update    # rewrite docs/programs.json
    python -m tools.ledger --check     # exit 1 on drift (CI form)

The manifest is defined on the CPU backend (kernel selection differs
on TPU), so this entry point pins ``JAX_PLATFORMS=cpu`` before any jax
import — run it anywhere, the bytes come out the same.
"""
from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    mode = argv[0] if argv else "--check"
    if mode not in ("--check", "--update"):
        print(__doc__, file=sys.stderr)
        return 2
    # must precede the jax import chain: the committed ledger is the
    # CPU-traced program set whatever machine regenerates it, at the
    # tier-1 virtual device count (tests/conftest.py pins 8 — the
    # fleet step's mesh, and therefore its fingerprint, depend on it).
    # FORCED, not defaulted: a shell-exported device count or program
    # knob would commit a manifest CI can never reproduce.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "").split()
    flags = [f for f in flags
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from paddle_tpu.analysis import ledger
    for knob in ledger.SCRUB_ENV:
        os.environ.pop(knob, None)

    if mode == "--update":
        path = ledger.update()
        print(f"wrote {path}", file=sys.stderr)
        return 0
    diffs = ledger.check()
    if diffs:
        print("docs/programs.json drift (run `python -m tools.ledger "
              "--update` if deliberate):", file=sys.stderr)
        for d in diffs:
            print(f"  {d}", file=sys.stderr)
        return 1
    print("ledger green", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
